#!/usr/bin/env python
"""serve_r8: live-window evidence for the production run controller.

One supervised saved run (DESIGN.md §22) on whatever backend the window
exposes: promotion every epoch behind the signed manifest, a budget
hot-swap published before launch (it must journal as applied at the
first epoch boundary with zero retraces), the endpoint answering
/healthz /status /promoted over real HTTP, and the stop document as the
only way the run ends.  The markdown artifact records the endpoint
bodies and the journaled control/promotion events — the committable
evidence that the daemon plane survives a real-TPU window, not just the
CPU e2e suite.

Exit 0 only when the daemon drained to exit 0, /healthz and /promoted
answered 200, the hot-swap applied, and no retrace events landed.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import threading
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from matcha_tpu.obs import read_journal  # noqa: E402
from matcha_tpu.serve import (  # noqa: E402
    Controller,
    ServeConfig,
    ServeEndpoint,
    write_control,
)


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
    except OSError:
        return None, None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--round", type=int, default=8)
    p.add_argument("--out", default=None,
                   help="markdown artifact (default benchmarks/serve_r{round}.md)")
    p.add_argument("--save-path", default=None,
                   help="run folder (default benchmarks/serve_run_r{round})")
    p.add_argument("--workers", type=int, default=16)
    p.add_argument("--deadline", type=float, default=300.0,
                   help="seconds to wait for /healthz and /promoted to go 200")
    args = p.parse_args(argv)
    out = args.out or os.path.join(
        REPO_ROOT, "benchmarks", f"serve_r{args.round}.md")
    save_path = args.save_path or os.path.join(
        REPO_ROOT, "benchmarks", f"serve_run_r{args.round}")
    shutil.rmtree(save_path, ignore_errors=True)

    name = f"serve_r{args.round}"
    config = {
        "name": name, "model": "mlp", "dataset": "synthetic",
        "dataset_kwargs": {"num_train": 256, "num_test": 32},
        "num_workers": args.workers, "graphid": 2, "batch_size": 16,
        # the stop document is the only way this run ends — the probe
        # publishes it once the endpoint has answered
        "epochs": 100000, "lr": 0.05, "warmup": False, "matcha": True,
        "budget": 0.5, "seed": 3, "checkpoint_every": 1, "eval_every": 0,
        "measure_comm_split": False, "savePath": save_path,
    }
    controller = Controller(ServeConfig(
        config=config, promote_every=1, backoff=0.5))
    # the hot-swap rides the first epoch boundary: published before launch
    write_control(controller.control_path, {"version": 1, "budget": 0.25})
    endpoint = ServeEndpoint({name: controller}).start()

    rc_box: dict = {}
    th = threading.Thread(
        target=lambda: rc_box.update(rc=controller.run()), daemon=True)
    th.start()
    answers: dict = {}
    deadline = time.time() + args.deadline
    while time.time() < deadline and len(answers) < 2 and th.is_alive():
        for path in ("/healthz", "/promoted"):
            code, body = _get(endpoint.port, path)
            if code == 200 and path not in answers:
                answers[path] = body
        time.sleep(0.5)
    status_code, status = _get(endpoint.port, "/status")
    write_control(controller.control_path, {"version": 2, "stop": True})
    th.join(timeout=args.deadline)
    if th.is_alive():  # the stop document was ignored — don't hang the window
        controller.shutdown()
        th.join(timeout=30.0)
    endpoint.stop()
    rc = rc_box.get("rc")

    events = read_journal(controller.journal_path) \
        if os.path.exists(controller.journal_path) else []
    controls = [{k: e.get(k) for k in ("action", "applied", "epoch",
                                       "version", "reason")}
                for e in events if e["kind"] == "control"]
    promotions = [{k: e.get(k) for k in ("action", "epoch", "metric",
                                         "serving_epoch")}
                  for e in events if e["kind"] == "promotion"]
    retraces = [e for e in events if e["kind"] == "retrace"]
    swap_applied = any(c["action"] == "apply" and c["applied"]
                       for c in controls)
    ok = (rc == 0 and "/healthz" in answers and "/promoted" in answers
          and swap_applied and not retraces)

    lines = [
        f"# serve_r{args.round}: supervised run controller, live window",
        "",
        f"- verdict: {'OK' if ok else 'FAILED'} (daemon exit {rc}, "
        f"lifetimes {controller.lifetimes}, "
        f"restarts {controller.restarts_used})",
        f"- config: mlp/synthetic, {args.workers} workers, graphid 2, "
        f"matcha budget 0.5 -> hot-swapped 0.25 (control v1)",
        f"- hot-swap applied: {swap_applied}; retrace events: "
        f"{len(retraces)} (zero-retrace contract)",
        f"- promotions journaled: {len(promotions)}",
        "",
        "## endpoint answers",
        "",
    ]
    for path in ("/healthz", "/promoted"):
        body = json.dumps(answers.get(path), sort_keys=True, default=str)
        lines.append(f"- `{path}`: "
                     f"{'200' if path in answers else 'never 200'} {body}")
    lines.append(f"- `/status`: {status_code} "
                 f"{json.dumps(status, sort_keys=True, default=str)}")
    lines += ["", "## journaled control events", ""]
    lines += [f"- {json.dumps(c, sort_keys=True)}" for c in controls] or ["- (none)"]
    lines += ["", "## journaled promotion events", ""]
    shown = promotions[:6] + ([] if len(promotions) <= 12
                              else [None]) + promotions[-6:] \
        if len(promotions) > 12 else promotions
    lines += [f"- (... {len(promotions) - 12} more ...)" if pr is None
              else f"- {json.dumps(pr, sort_keys=True)}"
              for pr in shown] or ["- (none)"]
    lines.append("")
    with open(out, "w") as f:
        f.write("\n".join(lines))
    print(f"serve_probe: wrote {out} (verdict "
          f"{'OK' if ok else 'FAILED'})")
    shutil.rmtree(save_path, ignore_errors=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
