#!/usr/bin/env python
"""Regenerate the committed reference journal ``benchmarks/events_ring8.jsonl``.

The journal is the schema pin: tier-1 validates it line by line
(``tests/test_obs.py``), so the format cannot drift silently.  It is the
exact ``events.jsonl`` of one CPU run — ring-8 MATCHA at budget 0.5, pure
gossip (lr 0) from an unsynced init, telemetry on — the same recipe the
obs test fixtures use.  Event *timings* (``t``, ``compile_seconds``) are
wall-clock and differ across regenerations by design; the schema, kind
sequence, and physics-derived payloads are deterministic (fixed seed).

The run carries a two-event membership churn (w3 leaves at epoch 2 and
rejoins at epoch 5) so the journal pins the elastic ``membership`` kind —
two events, both eagerly re-planned, bracketing the 8→7→8 live sets —
alongside the cost ledger's ``compile`` event from the v1→v2 bump.  It
also carries a fault-plan straggler (w5, period 4 over the 4-step epochs
⇒ participation pinned at exactly 0.25) so the v3 health plane has real
evidence to commit: one ``heartbeat`` per epoch and the streaming
detector's ``straggler`` ``anomaly`` verdicts naming w5.

The v4 ``attribution`` kind is pinned by a **planted heterogeneous-link
scenario**: the CPU run records no real comm split (``comm_time`` is 0),
so the estimator is fed a synthetic per-epoch comm series
``y = base + A·θ`` built from the run's own reconstructed activation
design matrix with θ = ``PLANTED_MATCHING_SECONDS`` (matching 1 priced
3× matching 0 — the link heterogeneity MATCHA exists to exploit).
Everything is seed-deterministic, so the journaled estimate recovers θ
up to the ridge bias, and the companion artifact
``benchmarks/measured_link_costs_ring8.json`` pins the PL009–011 surface.

The v6 serve plane (ISSUE 17) rides the same run through the REAL
``TrainerHarness`` boundary hook: promotion every 4 epochs (one
``promotion`` event — the consensus-mean snapshot promoted at epoch 4,
mid-churn), and one hot-swap ``control`` document (budget 0.5 → 0.35)
published at the epoch-6 boundary — after the rejoin re-fold, so the
membership pins stay untouched — applied as a value update with zero
retraces, carrying the re-based drift prediction for replay parity.

The v7 recovery plane (ISSUE 18) rides along too: the run checkpoints
every epoch (``checkpoint`` events, digest sidecars), and post-run the
newest generation is bit-flipped, convicted by its digest sidecar, and
quarantined — all through the REAL ladder helpers — with the resulting
``recovery`` event appended the way a resuming run journals it.

Regenerate after a journal schema bump (the v1→v2 bump of ISSUE 8 added
``compile`` events from the cost ledger; ISSUE 9 added ``membership``;
the v2→v3 bump of ISSUE 10 added ``heartbeat`` and ``anomaly``; the
v3→v4 bump of ISSUE 11 added ``attribution``; the v5→v6 bump of
ISSUE 17 added ``control`` and ``promotion``; the v6→v7 bump of
ISSUE 18 added ``recovery``):

    JAX_PLATFORMS=cpu python benchmarks/make_reference_journal.py
"""

import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the planted per-matching seconds-per-activation (θ) and per-epoch base —
#: the "heterogeneous links" the committed attribution event must recover
PLANTED_MATCHING_SECONDS = [0.02, 0.06]
PLANTED_BASE_SECONDS = 0.01

#: the v6 serve-plane pins: the hot-swap document's target budget and the
#: epoch boundary it is published at (after the epoch-5 rejoin re-fold),
#: and the promotion cadence (one promotion, at epoch 4)
SWAP_BUDGET = 0.35
SWAP_EPOCH = 6
PROMOTE_EVERY = 4


def main() -> int:
    from matcha_tpu.train import TrainConfig, train

    root = tempfile.mkdtemp(prefix="ref_journal_")
    cfg = TrainConfig(
        name="ring8", model="mlp", dataset="synthetic",
        description="reference journal: ring-8 MATCHA budget 0.5, "
                    "pure-gossip contraction (lr 0, unsynced init)",
        dataset_kwargs={"num_train": 256, "num_test": 32},
        num_workers=8, graphid=5, batch_size=8, epochs=8, lr=0.0,
        warmup=False, momentum=0.0, weight_decay=0.0, matcha=True,
        budget=0.5, seed=3, save=True, sync_init=False, eval_every=0,
        checkpoint_every=1, measure_comm_split=False,
        membership_trace={"name": "ref_churn", "events": [
            {"kind": "leave", "epoch": 2, "worker": "w3"},
            {"kind": "rejoin", "epoch": 5, "worker": "w3"},
        ]},
        # the health plane's committed evidence: a period-4 straggler on
        # w5 participates exactly 1 step in 4, so every heartbeat carries
        # participation 0.25 and every epoch convicts one `anomaly`
        fault_plan={"events": [
            {"kind": "straggler", "worker": 5, "start": 0, "period": 4},
        ]},
    )
    # v6 pin: the REAL serve plane as the boundary hook — the committed
    # `control` and `promotion` events come from TrainerHarness itself,
    # not hand-written dicts.  The control document is published at the
    # epoch-6 boundary through the atomic writer, so the journal commits
    # one applied value-scope swap (budget 0.5 → 0.35) and one promotion
    # (epoch 4, the consensus mean promoted mid-churn).
    from matcha_tpu.serve import TrainerHarness, write_control

    control_path = os.path.join(root, "control.json")
    harness = TrainerHarness({
        "control_path": control_path,
        "serving_dir": os.path.join(root, "serving"),
        "promote_every": PROMOTE_EVERY, "eval_batch": 32,
    })

    def boundary_hook(seam):
        if seam.epoch == SWAP_EPOCH:
            write_control(control_path,
                          {"version": 1, "budget": SWAP_BUDGET})
        harness.on_boundary(seam)

    # savePath stays the default relative "runs" so the journaled config
    # snapshot carries no machine-specific temp path — run from a tmp cwd
    os.chdir(root)
    train(cfg, boundary_hook=boundary_hook)
    src = os.path.join(root, "runs", "ring8_mlp", "events.jsonl")
    dst = os.path.join(REPO, "benchmarks", "events_ring8.jsonl")
    shutil.copyfile(src, dst)

    # v4 pin: attribute the planted heterogeneous-link scenario and append
    # the resulting `attribution` event (the schema evidence) plus the
    # companion measured_link_costs artifact (the planlint PL009-011 pin)
    import numpy as np

    from matcha_tpu.analysis import lint_link_costs_data
    from matcha_tpu.obs import append_journal_record, read_journal
    from matcha_tpu.obs.attribution import (
        attribute_run,
        attribution_event_fields,
        design_matrix,
        link_costs_artifact,
        reconstruct_schedule_arrays,
    )

    events = read_journal(dst)
    # the serve plane actually landed, through the real code paths: one
    # applied hot-swap at the pinned boundary (with the re-based drift
    # prediction for replay parity), one promotion, zero retraces
    [swap] = [e for e in events if e["kind"] == "control"]
    assert (swap["action"], swap["applied"], swap["epoch"]) \
        == ("apply", True, SWAP_EPOCH), swap
    assert swap["fields"]["budget"]["budget"] == SWAP_BUDGET
    assert 0.0 < swap["predicted"]["rho"] < 1.0, swap
    [promo] = [e for e in events if e["kind"] == "promotion"]
    assert (promo["action"], promo["epoch"]) == ("promote", PROMOTE_EVERY)
    assert not [e for e in events if e["kind"] == "retrace"]
    start = next(e for e in events if e["kind"] == "run_start")
    spe = int(start["predicted"]["steps_per_epoch"])
    epochs = sorted(e["epoch"] for e in events if e["kind"] == "epoch")
    flags, _, _, _ = reconstruct_schedule_arrays(
        start["config"], (max(epochs) + 1) * spe + 1)
    A = design_matrix(flags, spe, epochs)
    y = PLANTED_BASE_SECONDS + A @ np.asarray(PLANTED_MATCHING_SECONDS)
    report = attribute_run(events, comm_seconds=y,
                           source="planted:ring8-hetero")
    assert all(report["identifiable"]), report["reason"]
    recovered = np.asarray(report["per_matching_seconds"])
    assert np.allclose(recovered, PLANTED_MATCHING_SECONDS, atol=1e-4), \
        f"planted {PLANTED_MATCHING_SECONDS} vs recovered {recovered}"
    append_journal_record(dst, "attribution",
                          **attribution_event_fields(report))
    costs_path = os.path.join(REPO, "benchmarks",
                              "measured_link_costs_ring8.json")
    artifact = link_costs_artifact(report)
    violations = lint_link_costs_data(artifact, costs_path)
    assert not violations, violations
    with open(costs_path, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")

    # v7 pin: the recovery ladder through the REAL helpers — flip one bit
    # in the newest checkpoint generation, let the digest sidecar convict
    # it, quarantine it aside, and journal the move exactly the way a
    # resuming run does (never a hand-written dict)
    import random

    from matcha_tpu.chaos.injectors import bitflip_checkpoint
    from matcha_tpu.train.checkpoint import (
        latest_step,
        quarantine_step,
        verify_checkpoint_digest,
    )

    ckpt = os.path.join(root, "runs", "ring8_ckpt")
    step = latest_step(ckpt)
    assert step == cfg.epochs - 1, step
    assert verify_checkpoint_digest(ckpt, step) == []
    bitflip_checkpoint(ckpt, step, random.Random(0))
    problems = verify_checkpoint_digest(ckpt, step)
    assert problems, "the digest sidecar must convict the flipped bit"
    qdir = quarantine_step(ckpt, step)
    assert latest_step(ckpt) == step - 1  # the ladder's next rung
    append_journal_record(
        dst, "recovery", scope="checkpoint", action="quarantine",
        reason=f"digest verification failed: {problems[0]}", epoch=step,
        quarantined=os.path.join("runs", "ring8_ckpt",
                                 os.path.basename(qdir)))
    print(f"reference journal regenerated: {dst}")
    print(f"reference link costs regenerated: {costs_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
