#!/bin/sh
# Prioritized measurement plan for a live-TPU window (the axon tunnel is
# intermittent — run the highest-value artifacts first; each step is
# independently committable).  From the repo root: sh benchmarks/tpu_session.sh
set -x

# 0. liveness + correctness gate: backend is a real TPU, the Pallas fused
#    kernel reproduces dense on-device, one folded shard_map step matches the
#    oracle.  A failed/timed-out gate must NOT abort before bench.py — the
#    bench self-protects and always emits a structured artifact (its CPU
#    provisional); the gate only gates the *expensive tuning* steps below.
timeout 240 python benchmarks/tpu_gate.py --out benchmarks/tpu_gate.json; GATE_RC=$?

# 1. THE driver artifact: per-step primary + chunked secondary (≤ ~9 min);
#    runs even on a broken tunnel (bounded attempts + CPU provisional)
python bench.py
[ "$GATE_RC" -eq 0 ] || { echo "gate failed (rc=$GATE_RC): skipping tuning steps"; exit 1; }

# 2. per-step kernel tuning toward the ≥5k north star: block_d sweep, then
#    W-window sweep at the winning block size (each ≤ ~4 min)
python bench.py --block-d 0
python bench.py --w-window 2
python bench.py --w-window 4
python bench.py --w-window 8

# 3. full-train-step throughput + gossip marginal at the north-star config
#    (--remat: the un-rematted 256x32 backward over-allocates v5e HBM)
python benchmarks/train_step_bench.py --remat --out benchmarks/train_step_bench.json

# 4. regenerate the timing artifacts with reps/noise bands (VERDICT r2 #7)
python benchmarks/time_to_acc.py --reps 2
python benchmarks/budget_sweep.py --reps 2

# 5. converge tier for the configs a 1-core CPU cannot train (VERDICT r2 #3)
#    — including the 256-images-per-worker CHOCO rerun of config 4, whose
#    64-image-shard CPU probes plateaued (see baselines_converge.jsonl)
python benchmarks/run_baselines.py --scale converge \
    --only dpsgd-resnet-cifar10-8w,matcha-vgg16-cifar10-8w,matcha-wrn-cifar100-16w,choco-resnet-cifar10-64w,matcha-resnet50-imagenet-256w \
    --out benchmarks/baselines_converge.jsonl

# 6. refresh the skip microbench (masked-control discipline)
python benchmarks/skip_microbench.py
