#!/bin/sh
# Prioritized measurement plan for a live-TPU window (the axon tunnel is
# intermittent — run the highest-value artifacts first; each step is
# independently committable).  From the repo root: sh benchmarks/tpu_session.sh
set -x

# 0. liveness gate (seconds)
timeout 90 python -c "import jax; print(jax.devices())" || exit 1

# 1. THE driver artifact: per-step primary + chunked secondary (≤ ~6 min)
python bench.py

# 2. per-step kernel tuning toward the ≥5k north star: block_d sweep, then
#    W-window sweep at the winning block size (each ≤ ~4 min)
python bench.py --block-d 0
python bench.py --w-window 2
python bench.py --w-window 4
python bench.py --w-window 8

# 3. full-train-step throughput + gossip marginal at the north-star config
python benchmarks/train_step_bench.py --out benchmarks/train_step_bench.json

# 4. regenerate the timing artifacts with reps/noise bands (VERDICT r2 #7)
python benchmarks/time_to_acc.py --reps 2
python benchmarks/budget_sweep.py --reps 2

# 5. converge tier for the configs a 1-core CPU cannot train (VERDICT r2 #3)
#    — including the 256-images-per-worker CHOCO rerun of config 4, whose
#    64-image-shard CPU probes plateaued (see baselines_converge.jsonl)
python benchmarks/run_baselines.py --scale converge \
    --only dpsgd-resnet-cifar10-8w,matcha-vgg16-cifar10-8w,matcha-wrn-cifar100-16w,choco-resnet-cifar10-64w,matcha-resnet50-imagenet-256w \
    --out benchmarks/baselines_converge.jsonl

# 6. refresh the skip microbench (masked-control discipline)
python benchmarks/skip_microbench.py
