#!/bin/sh
# Prioritized measurement plan for a live-TPU window (the axon tunnel is
# intermittent — run the highest-value artifacts first; each step is
# independently committable).  From the repo root: sh benchmarks/tpu_session.sh
#
# r4 reordering: the fused-kernel tuning grid is already committed
# (fused_sweep.json, 12+6 points — bench.py defaults are its winner), so the
# open items move up: the full-train-step number and the converge tier
# (CHOCO-at-64w convergence, configs 2/3 curves) now come right after the
# driver artifact.
set -x

# 0. liveness + correctness gate: backend is a real TPU, the Pallas fused
#    kernel reproduces dense on-device, one folded shard_map step matches the
#    oracle.  Persists passing evidence to benchmarks/tpu_gate.json.  A
#    failed/timed-out gate must NOT abort before bench.py — the bench
#    self-protects and always emits a structured artifact (its CPU
#    provisional); the gate only gates the *expensive tuning* steps below.
timeout -k 30 240 python benchmarks/tpu_gate.py --out benchmarks/tpu_gate.json; GATE_RC=$?

# 0.1 clean-lint stamp: record that the tree this session measured passes
#     graftlint (static invariants + empty baseline) next to the bench
#     captures — a bench number from a tree that violates the wire-seam or
#     masking invariants is not evidence.  Pure host work, tunnel-immune;
#     the stamp carries clean=true/false either way.
timeout -k 10 120 python lint_tpu.py --format json > benchmarks/lint_stamp_r6.json \
    || echo "lint stamp: violations recorded in benchmarks/lint_stamp_r6.json"
#     ... and the graftcontract verdict next to it (ISSUE 15): the
#     sync-budget prover against the committed sync_budget.json manifest,
#     journal-schema call sites, checkpoint-evolution coverage — a bench
#     number from a tree that sneaks a per-step host sync past the budget
#     is measuring a different program than the one the docs describe.
timeout -k 10 120 python lint_tpu.py --rules GL201,GL202,GL203 --format json \
    > benchmarks/contracts_stamp_r6.json \
    || echo "graftcontract: violations recorded in benchmarks/contracts_stamp_r6.json"
#     ... and that the committed plan artifacts still verify numerically
#     (PL001–PL008): a bench driven by a stale/tampered plan JSON measures
#     a schedule the solver never scored.
timeout -k 10 120 python lint_tpu.py lint-plan \
    || echo "lint-plan: committed plan artifact(s) FAILED verification"

# 0.2 obs stamp: every bench invocation this session also mirrors its
#     final record into the session journal (bench.py --journal), so the
#     round's numbers become `bench` events obs_tpu.py can compare against
#     past rounds and against training-run journals.  After the captures,
#     step 6 renders the comparison as a committable markdown artifact.
OBS_JOURNAL=benchmarks/bench_journal_r6.jsonl

# 1. THE driver artifact: per-step primary + chunked secondary + the
#    overlap × wire-dtype grid (bench.py now emits `overlap_grid` by
#    default: eager|1step × f32|bf16 cells with rate + bytes_per_step);
#    runs even on a broken tunnel (bounded attempts + CPU provisional).
#    capture_live persists an on-TPU record as bench_live_r6.json — the
#    committed hardware evidence the fallback path cites, now carrying the
#    combined overlap+bf16 speedup as the headline ask of this window.
python benchmarks/capture_live.py --round 6 -- --journal "$OBS_JOURNAL"
[ "$GATE_RC" -eq 0 ] || { echo "gate failed (rc=$GATE_RC): skipping tuning steps"; exit 1; }

# 1.5 overlap × wire-dtype at the *training* regime: the pipelined train
#     step (--overlap 1step) only pays off where there is ICI to hide —
#     time eager vs pipelined, f32 vs bf16 wire, on whatever mesh the
#     window exposes (--backend auto: shard_map on a multi-chip mesh,
#     dense on a single chip — the step must still land evidence on the
#     1-chip windows every round so far has had).  Cheap (4 short runs);
#     the per-epoch JSON lines are PERSISTED as the committable artifact —
#     a headline number that only scrolls past in the session log is the
#     promissory-claim failure mode tests/test_docs_artifacts.py exists
#     to prevent.
rm -f benchmarks/overlap_sweep_r6.jsonl
# one bounded device-count probe, hoisted: jax.devices() is exactly the RPC
# the tunnel's stall mode wedges, so it must never run unwrapped (and never
# 4 times) inside the loop
DEVS=$(timeout -k 10 120 python -c 'import jax; print(len(jax.devices()))' 2>/dev/null)
for ov in off 1step; do for wd in f32 bf16; do
    echo "{\"sweep\": \"overlap-x-wire r6\", \"overlap\": \"$ov\", \"wire_dtype\": \"$wd\", \"devices\": \"$DEVS\"}" \
        >> benchmarks/overlap_sweep_r6.jsonl
    timeout -k 30 420 python train_tpu.py --name "ovgrid-$ov-$wd" \
        --model mlp --dataset synthetic --graphid 2 --numworkers 16 \
        --epoch 3 --backend auto --overlap "$ov" --wire-dtype "$wd" \
        --no-comm-split >> benchmarks/overlap_sweep_r6.jsonl
done; done

# Every step below is timeout-wrapped: the tunnel's observed failure mode
# (r4) is a mid-RPC stall that hangs the client forever — an unwrapped step
# would wedge the whole session on the first stall and lose the later steps.

# 1.6 roofline_r6 + profile_r6 (ISSUE 8: the performance-observability
#     artifacts ROADMAP's "queued live artifacts" item asks for).
#     roofline: compiled-cost ceilings at the north-star shape against the
#     real chip's pinned peaks, with the measured rate from this round's
#     bench journal — the measured/ceiling ratio is the Pallas-promotion
#     gate number.  profile: trace one overlapped and one non-overlapped
#     short train window and parse the executed kernels for the comm/comp
#     overlap fraction — the first hardware answer to whether --overlap
#     1step actually hides the exchange (obs_tpu.py profile exits 2 on a
#     device-row-less trace, so a tunnel that fell back to CPU records an
#     explicit failure, never a fake 0%).
timeout -k 10 300 python obs_tpu.py roofline --source "$OBS_JOURNAL" \
    --md benchmarks/roofline_r6.md \
    || echo "roofline_r6: no finite ceilings (see stderr)"
rm -rf benchmarks/trace_r6_off benchmarks/trace_r6_1step
for ov in off 1step; do
    timeout -k 30 420 python train_tpu.py --name "profgrid-$ov" \
        --model mlp --dataset synthetic --graphid 2 --numworkers 16 \
        --epoch 3 --backend auto --overlap "$ov" --no-comm-split \
        --trace-dir "benchmarks/trace_r6_$ov" > /dev/null
done
timeout -k 10 120 python obs_tpu.py profile \
    benchmarks/trace_r6_off benchmarks/trace_r6_1step \
    --md benchmarks/profile_r6.md --journal "$OBS_JOURNAL" \
    || echo "profile_r6: trace carried no device rows (CPU fallback?)"

# 1.7 health_r6 (ISSUE 10: the live health plane's first on-TPU evidence).
#     One short *saved* run so heartbeats land under {run}/health/, then
#     the watch --once table as a committable markdown artifact — the
#     per-worker alive/rate/participation table README's "Live health"
#     section cites as queued.  A healthy fleet exits 0; a nonzero rc
#     here on real hardware is itself a finding worth committing.
rm -rf benchmarks/health_run_r6
timeout -k 30 420 python train_tpu.py --name health_r6 \
    --model mlp --dataset synthetic --graphid 2 --numworkers 16 \
    --epoch 3 --backend auto --no-comm-split \
    --save --savePath benchmarks/health_run_r6 > /dev/null
timeout -k 10 120 python obs_tpu.py watch benchmarks/health_run_r6/health_r6_mlp \
    --once --md benchmarks/health_r6.md \
    || echo "health_r6: fleet flagged or no heartbeats (see table/stderr)"
rm -rf benchmarks/health_run_r6

# 1.8 attrib_r7 + timeline_r7 (ISSUE 11: the attribution plane's first
#     on-TPU evidence).  One saved run WITH the comm split on (the
#     two-program timer is exactly the per-epoch comm signal the estimator
#     regresses; more epochs than matchings so the design is identifiable),
#     then: attribute -> the measured per-matching seconds artifact +
#     markdown (exit 1 = honestly unidentifiable, itself worth recording),
#     and timeline -> the scrub-in-Perfetto trace of the same run.  On
#     real ICI this is the first measured per-link heterogeneity number —
#     the input the reactive planner (ROADMAP health->plan loop) consumes.
rm -rf benchmarks/attrib_run_r7
timeout -k 30 600 python train_tpu.py --name attrib_r7 \
    --model mlp --dataset synthetic --graphid 2 --numworkers 16 \
    --epoch 8 --backend auto \
    --save --savePath benchmarks/attrib_run_r7 > /dev/null
timeout -k 10 180 python obs_tpu.py attribute \
    benchmarks/attrib_run_r7/attrib_r7_mlp \
    --out benchmarks/measured_link_costs_r7.json \
    --md benchmarks/attrib_r7.md \
    || echo "attrib_r7: unidentifiable or unusable journal (see stderr)"
timeout -k 10 180 python obs_tpu.py timeline \
    benchmarks/attrib_run_r7/attrib_r7_mlp \
    --out benchmarks/timeline_r7.json \
    || echo "timeline_r7: trace validation failed (see stderr)"
rm -rf benchmarks/attrib_run_r7

# 1.9 async_bench_r7 (ISSUE 14: bounded-staleness on real hardware).  The
#     bench's staleness grid (k in {1,2,4} x local_steps in {1,4}) rides
#     the driver artifact already; this step captures the *training-loop*
#     async evidence: eager barrier vs --staleness 2 vs --staleness 2
#     --local-steps 4 on whatever mesh the window exposes, per-epoch JSON
#     lines persisted as the committable artifact (the same
#     promissory-claim discipline as overlap_sweep).  On a single chip the
#     k-ring cannot buy wall-clock (no straggler to decouple from) — the
#     cells still pin ring overhead ~0 and the damped-alpha convergence;
#     the modeled recovery claim stays with the staleness grid.
rm -f benchmarks/async_bench_r7.json
for st in "1 1" "2 1" "2 4"; do
    set -- $st
    echo "{\"sweep\": \"async r7\", \"staleness\": $1, \"local_steps\": $2}" \
        >> benchmarks/async_bench_r7.json
    timeout -k 30 420 python train_tpu.py --name "async-k$1-l$2" \
        --model mlp --dataset synthetic --graphid 2 --numworkers 16 \
        --epoch 3 --backend auto --overlap 1step --staleness "$1" \
        --local-steps "$2" --no-comm-split >> benchmarks/async_bench_r7.json
done

# 1.95 serve_r8 (ISSUE 17: the production run controller's first on-TPU
#      evidence).  One supervised saved run with promotion every epoch, a
#      budget hot-swap published before launch (must journal as applied
#      with zero retraces — the zero-retrace contract on the real
#      backend), /healthz and /promoted answered over HTTP, and the stop
#      document draining the daemon to exit 0; the probe renders the
#      endpoint bodies and the journaled control/promotion events as the
#      committable markdown artifact.
timeout -k 30 600 python benchmarks/serve_probe.py --round 8 \
    --out benchmarks/serve_r8.md \
    || echo "serve_r8: controller probe failed (see benchmarks/serve_r8.md)"

# 1.96 chaos_r8 (ISSUE 18: the host-plane chaos campaign's committable
#      verdict).  26 seeded trials — two full rotations through every
#      injector family (checkpoint corruption, torn/corrupt journal,
#      torn control publish, kills at the four seeded barriers, ENOSPC /
#      hung heartbeat IO, clock skew) — against the real supervised
#      daemon, judged by the pinned invariant suite.  The faults all
#      live on the host/storage plane, so the verdict is
#      accelerator-independent; running it inside the TPU window pins
#      that the recovery ladder behaves identically when orbax holds
#      device arrays.  Failing seeds print in the artifact with their
#      exact replay command.
rm -rf benchmarks/chaos_run_r8
timeout -k 30 1800 python chaos_tpu.py campaign --trials 26 \
    --workdir benchmarks/chaos_run_r8 --md benchmarks/chaos_r8.md \
    || echo "chaos_r8: campaign FAILED (see benchmarks/chaos_r8.md)"
rm -rf benchmarks/chaos_run_r8

# 1.97 elision_r8 + profile_overlap_r8 (ISSUE 19: universal local-step
#      elision + double-buffered perm windows on real hardware).
#      elision_r8.json: the bench's elision_grid (skip/dense/perm x
#      local_every in {1,4}) rides the driver record — measured
#      gossip-steps/s next to the ledger's per-epoch boundary bytes, the
#      A/B the >=2x byte-reduction claim ships with (tests pin the CPU
#      arithmetic; this captures the TPU rates).  profile_overlap_r8.md:
#      trace two short perm-backend train windows (overlap off vs 1step;
#      the perm kernel double-buffers its flag-row window DMA by default)
#      and parse executed kernels for the comm/comp overlap fraction —
#      the hardware answer to whether the dbuf window prefetch holds the
#      >=90% target the trace fixtures pin at 95% (acceptance floor 75%).
timeout -k 30 900 python bench.py --elision-grid-steps 120 \
    --journal "$OBS_JOURNAL" | tail -n 1 > benchmarks/elision_r8.json
rm -rf benchmarks/trace_r8_off benchmarks/trace_r8_1step
for ov in off 1step; do
    timeout -k 30 420 python train_tpu.py --name "permdbuf-$ov" \
        --model mlp --dataset synthetic --graphid 2 --numworkers 16 \
        --epoch 3 --backend perm --overlap "$ov" --no-comm-split \
        --trace-dir "benchmarks/trace_r8_$ov" > /dev/null
done
timeout -k 10 120 python obs_tpu.py profile \
    benchmarks/trace_r8_off benchmarks/trace_r8_1step \
    --md benchmarks/profile_overlap_r8.md --journal "$OBS_JOURNAL" \
    || echo "profile_overlap_r8: no device rows (CPU fallback?)"

# 2. full-train-step throughput + gossip marginal at the north-star config
#    (--remat + slab 32: the un-rematted 256x32 backward over-allocates v5e
#    HBM).  Generous bound: the program compiles are the cost; they persist
#    in the compile cache, so even a timed-out attempt pays forward.
timeout -k 30 1500 python benchmarks/train_step_bench.py --remat --grad-chunk 32 \
    --out benchmarks/train_step_r5.json

# 2.2 >HBM scale probe (docs/DESIGN.md scale section, VERDICT r5 item 6):
#     the largest BASELINE-config-5-shaped setup that fits ONE v5e —
#     64 virtual workers x ResNet-50@224 (f32 state+momentum ~13 GB) with
#     remat + 8-worker fwd/bwd slabs; 256 workers needs the C>=4-chip
#     folded plan (see the DESIGN.md arithmetic), which this chip count
#     cannot host — the dryrun_multichip path covers its program instead.
timeout -k 30 1500 python benchmarks/train_step_bench.py --model resnet50 \
    --image-size 224 --classes 1000 --workers 64 --batch 2 --steps 2 \
    --remat --grad-chunk 8 --out benchmarks/scale_probe_r5.json

# 2.5 kernel-scheduling probe (after the headline: a probe stall must not cost step 2): can the per-step cast overlap the MXU via
#     column splitting? (candidate for closing the last ~9% to the per-step
#     ceiling — integrate into pallas_gossip only if this measures a win)
timeout -k 30 420 python benchmarks/split_probe.py --out benchmarks/split_probe.json

# 2.55 permutation-form kernel A/B: the probe now re-exports the
#      PRODUCTION perm backend (matcha_tpu.parallel.perm_gossip_run —
#      gossip_backend="perm" since ISSUE 13), so this times the same
#      program text training runs; the correctness gate still withholds
#      the ratio on divergence
timeout -k 30 420 python benchmarks/perm_probe.py --out benchmarks/perm_probe.json

# 2.56 perm backend bench cell + the perm-vs-fused roofline.  The bench
#      record carries the flag-stream bytes_per_step and the
#      matching_wire_bytes exchanged-row account; the roofline compare
#      emits both kernels' ceilings from extracted compiled costs with
#      the measured ratio naming its denominator backend — together they
#      are the choose_gossip_backend gate's evidence pair.
timeout -k 30 600 python bench.py --backend perm --journal "$OBS_JOURNAL" \
    | tail -n 1 > benchmarks/perm_bench_r7.json
timeout -k 10 300 python obs_tpu.py roofline --backend both \
    --source benchmarks/perm_bench_r7.json \
    --md benchmarks/roofline_perm_r7.md \
    || echo "perm roofline: non-finite ceiling (see stderr)"

# 2.6 CHOCO encode cost: exact vs TPU-native approximate top-k (and the
#     other registry compressors) at the config-4 shape
timeout -k 30 420 python benchmarks/encode_bench.py --out benchmarks/encode_bench.json

# 3. converge tier, highest-value configs first: the 256-images-per-worker
#    CHOCO rerun of config 4 (VERDICT r3 item 3 — the 64-image-shard CPU
#    probes plateaued; see baselines_converge.jsonl), then configs 2/3
#    (VERDICT r3 item 4), then the rest.  One invocation per config so a
#    dying tunnel loses at most the in-flight run.
#    Budgets: the CPU-measured converge runs took 5,000-8,100 s (64w
#    configs); on TPU the epochs collapse but the compile is the cost, so
#    each config gets an hour (the run_baselines SIGTERM handler records an
#    explicit error line if the budget still isn't enough) and -k guarantees
#    a KILL if the tunnel stall leaves the client unkillable-by-TERM.
#    r5 ordering: the compression-warmup fix for the config-4 plateau and
#    the real-RGB-pixel photo configs lead (VERDICT r5 items 1 and 4).
for c in choco-resnet-cifar10-64w-warmup matcha-resnet-photo-8w \
         choco-resnet-cifar10-64w dpsgd-resnet-photo-8w \
         central-resnet-photo-8w choco-resnet-cifar10-64w-512shard \
         matcha-vgg16-cifar10-8w \
         matcha-wrn-cifar100-16w dpsgd-resnet-cifar10-8w \
         matcha-resnet50-imagenet-256w matcha-mlp-digits-8w; do
    timeout -k 30 3600 python benchmarks/run_baselines.py --scale converge \
        --only "$c" --out benchmarks/baselines_converge.jsonl
done

# 4. regenerate the timing artifacts with reps/noise bands
timeout -k 30 1200 python benchmarks/time_to_acc.py --reps 2
timeout -k 30 1200 python benchmarks/budget_sweep.py --reps 2

# 5. refresh the skip microbench (masked-control discipline)
timeout -k 30 600 python benchmarks/skip_microbench.py

# 6. obs stamp render: one table across this round's journal, every
#    committed BENCH_r* record, and the measured link-costs artifacts
#    (committed reference + this round's capture when step 1.8 landed one)
#    — the cross-round comparison obs_tpu.py compare exists for, persisted
#    as a committable markdown artifact.
timeout -k 10 120 python obs_tpu.py compare "$OBS_JOURNAL" BENCH_r0*.json \
    benchmarks/measured_link_costs*.json \
    --md benchmarks/obs_compare_r6.md \
    || echo "obs compare: no comparable records (journal missing?)"
