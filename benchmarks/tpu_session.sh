#!/bin/sh
# Prioritized measurement plan for a live-TPU window (the axon tunnel is
# intermittent — run the highest-value artifacts first; each step is
# independently committable).  From the repo root: sh benchmarks/tpu_session.sh
#
# r4 reordering: the fused-kernel tuning grid is already committed
# (fused_sweep.json, 12+6 points — bench.py defaults are its winner), so the
# open items move up: the full-train-step number and the converge tier
# (CHOCO-at-64w convergence, configs 2/3 curves) now come right after the
# driver artifact.
set -x

# 0. liveness + correctness gate: backend is a real TPU, the Pallas fused
#    kernel reproduces dense on-device, one folded shard_map step matches the
#    oracle.  Persists passing evidence to benchmarks/tpu_gate.json.  A
#    failed/timed-out gate must NOT abort before bench.py — the bench
#    self-protects and always emits a structured artifact (its CPU
#    provisional); the gate only gates the *expensive tuning* steps below.
timeout 240 python benchmarks/tpu_gate.py --out benchmarks/tpu_gate.json; GATE_RC=$?

# 1. THE driver artifact: per-step primary + chunked secondary (≤ ~9 min);
#    runs even on a broken tunnel (bounded attempts + CPU provisional)
python bench.py
[ "$GATE_RC" -eq 0 ] || { echo "gate failed (rc=$GATE_RC): skipping tuning steps"; exit 1; }

# 2. full-train-step throughput + gossip marginal at the north-star config
#    (--remat: the un-rematted 256x32 backward over-allocates v5e HBM)
python benchmarks/train_step_bench.py --remat --out benchmarks/train_step_bench.json

# 3. converge tier, highest-value configs first: the 256-images-per-worker
#    CHOCO rerun of config 4 (VERDICT r3 item 3 — the 64-image-shard CPU
#    probes plateaued; see baselines_converge.jsonl), then configs 2/3
#    (VERDICT r3 item 4), then the rest.  One invocation per config so a
#    dying tunnel loses at most the in-flight run.
for c in choco-resnet-cifar10-64w matcha-vgg16-cifar10-8w \
         matcha-wrn-cifar100-16w dpsgd-resnet-cifar10-8w \
         matcha-resnet50-imagenet-256w; do
    python benchmarks/run_baselines.py --scale converge --only "$c" \
        --out benchmarks/baselines_converge.jsonl
done

# 4. regenerate the timing artifacts with reps/noise bands
python benchmarks/time_to_acc.py --reps 2
python benchmarks/budget_sweep.py --reps 2

# 5. refresh the skip microbench (masked-control discipline)
python benchmarks/skip_microbench.py
