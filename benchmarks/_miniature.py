"""Shared base config for the miniature paper-scale experiments.

budget_sweep.py and time_to_acc.py make claims that are only meaningful if
they run the *same* experiment (model, data, workers, topology, lr, seed) —
budget_sweep compares accuracy across budgets, time_to_acc compares
wall-clock across communicators at one budget.  This helper is the single
source of truth for that shared setup; each harness overrides only the axis
it sweeps.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from matcha_tpu.train import TrainConfig  # noqa: E402


def miniature_config(name: str, epochs: int, **overrides) -> TrainConfig:
    """ResNet-20 on synthetic CIFAR-shaped clusters, 16 workers, zoo
    geometric graph (graphid 2) — the miniature stand-in for the paper's
    CIFAR-10 experiments, sized to finish in minutes on one TPU chip."""
    base = dict(
        name=name,
        model="resnet20", dataset="synthetic_image", batch_size=8,
        # stronger cluster separation: CIFAR-sized convnets need a per-pixel
        # signal a 3×3-local stem can pick up within a miniature epoch budget
        dataset_kwargs={"num_train": 4096, "num_test": 1024, "separation": 40.0},
        num_workers=16, graphid=2, fixed_mode="all",
        lr=0.05, base_lr=0.05, warmup=False, epochs=epochs,
        decay_epochs=(int(epochs * 0.6), int(epochs * 0.8)),
        save=False, eval_every=1, measure_comm_split=True, seed=1,
    )
    base.update(overrides)
    return TrainConfig(**base)


def timing_stats(values):
    """Mean plus the observed cross-rep noise band for a wall-clock quantity.

    The tunneled chip shows ±10-15% run-to-run noise (VERDICT r2 item 7): a
    claimed 1.1-1.2× speedup is meaningless without the band that could
    manufacture or erase it, so every committed timing carries its reps and
    ``band = (max − min) / mean``."""
    vals = [float(v) for v in values]
    mean = sum(vals) / len(vals)
    return {
        "mean": round(mean, 4),
        "reps": [round(v, 4) for v in vals],
        "band": round((max(vals) - min(vals)) / max(mean, 1e-9), 4),
    }


def ratio_range(numers, denoms):
    """[worst, best] ratio over rep pairings — the honest bounds a
    mean-over-mean ratio lives inside."""
    lo = min(numers) / max(max(denoms), 1e-9)
    hi = max(numers) / max(min(denoms), 1e-9)
    return [round(lo, 3), round(hi, 3)]
