#!/usr/bin/env python
"""Step-0 TPU gate for the live-window runbook (VERDICT r3 item 7).

A cheap on-device correctness check that runs BEFORE the expensive bench
steps, so a broken kernel or backend surfaces as a named failure instead of
burning the session budget:

  1. backend is a real TPU (not the CPU fallback);
  2. the Pallas fused kernel reproduces the dense MXU path on-device at
     small N (the first non-``interpret=True`` assertion of fused == dense —
     every ``tests/test_pallas.py`` run is CPU-interpreted by construction);
  3. one folded shard_map gossip step matches the dense oracle on-device.

Prints one JSON line; exit 0 = gate open, non-zero = named failure.
Wall-clock is dominated by 3 small TPU compiles (~1-2 min cold).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# invoked as `python benchmarks/tpu_gate.py`: sys.path[0] is benchmarks/,
# and matcha_tpu is not pip-installed — put the repo root on the path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_OUT: str | None = None


def emit(record: dict) -> None:
    record = dict(record, when=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    print(json.dumps(record))
    # Persist only passing records: --out is the committed evidence that the
    # kernel was validated on-device, and a transient dead-tunnel failure
    # (the expected flaky-window mode) must not clobber it.  Failures still
    # go to stdout + exit code, which is what the runbook gates on.  Write
    # via temp + rename: the runbook's `timeout` SIGTERM landing mid-dump
    # must not truncate previously committed evidence either.
    if _OUT and record.get("ok"):
        tmp = _OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
        os.replace(tmp, _OUT)


def fail(stage: str, detail: str) -> int:
    emit({"gate": "tpu", "ok": False, "stage": stage, "detail": detail[-300:]})
    return 1


def main() -> int:
    t0 = time.time()
    try:
        import jax
        import jax.numpy as jnp

        kind = jax.devices()[0].device_kind
    except Exception as e:  # noqa: BLE001 — any backend-init failure is the finding
        return fail("backend_init", f"{type(e).__name__}: {e}")
    if "tpu" not in kind.lower():
        return fail("backend_kind", f"device_kind={kind!r} is not a TPU")

    from matcha_tpu import topology as tp
    from matcha_tpu.communicator import make_decen
    from matcha_tpu.parallel import worker_mesh
    from matcha_tpu.schedule import matcha_schedule

    n, dim, steps = 16, 4096, 20
    edges = tp.make_graph("geometric", n, seed=1)
    dec = tp.decompose(edges, n, seed=1)
    sched = matcha_schedule(dec, n, iterations=steps, budget=0.5, seed=0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(n, dim)).astype(np.float32))
    flags = jnp.asarray(sched.flags, jnp.float32)

    def run(backend, **kw):
        comm = make_decen(sched, backend=backend, compute_dtype=jnp.float32, **kw)
        out, _ = jax.jit(lambda x: comm.run(x, flags))(x)
        return np.asarray(jax.device_get(out), np.float32)

    try:
        ref = run("dense")
    except Exception as e:  # noqa: BLE001
        return fail("dense_backend", f"{type(e).__name__}: {e}")
    try:
        fused = run("fused", block_d=2048)
    except Exception as e:  # noqa: BLE001
        return fail("pallas_compile", f"{type(e).__name__}: {e}")
    err = float(np.max(np.abs(fused - ref)) / max(1e-12, np.max(np.abs(ref))))
    if err > 1e-5:
        return fail("pallas_mismatch", f"fused vs dense rel err {err:.2e} on {kind}")
    try:
        folded = run("shard_map", mesh=worker_mesh())
    except Exception as e:  # noqa: BLE001
        return fail("shard_map", f"{type(e).__name__}: {e}")
    err_sm = float(np.max(np.abs(folded - ref)) / max(1e-12, np.max(np.abs(ref))))
    if err_sm > 1e-5:
        return fail("shard_map_mismatch", f"rel err {err_sm:.2e} on {kind}")

    emit({
        "gate": "tpu", "ok": True, "device_kind": kind,
        "fused_vs_dense_rel_err": err, "shard_map_vs_dense_rel_err": err_sm,
        "n": n, "dim": dim, "steps": steps,
        "wall_s": round(time.time() - t0, 1),
    })
    return 0


if __name__ == "__main__":
    _p = argparse.ArgumentParser()
    _p.add_argument("--out", default=None,
                    help="also write the gate record to this JSON file")
    _OUT = _p.parse_args().out
    sys.exit(main())
