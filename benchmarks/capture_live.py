#!/usr/bin/env python
"""Run `python bench.py` and, when the final record is a real on-TPU
measurement, persist it verbatim as benchmarks/bench_live_r{N}.json — the
committed hardware-evidence artifact the bench fallback path cites
(bench.py orchestrate: last_live_artifact).  Round 4 captured this by hand;
automating it means any live window the session catches leaves the artifact
even if the tunnel dies minutes later.

Usage: python benchmarks/capture_live.py --round 5 [-- extra bench args]
Exit code: bench.py's (the capture itself never fails the session).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--round", type=int, required=True)
    args, bench_args = p.parse_known_args()
    if bench_args and bench_args[0] == "--":
        # parse_known_args leaves the documented `--` separator in the
        # unknown list (ADVICE r5); forwarding it literally would feed
        # bench.py a bogus positional
        bench_args = bench_args[1:]
    args.bench_args = bench_args  # everything else passes through to bench.py
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)

    # stream bench.py's stdout line-by-line (tee semantics): the provisional
    # record must reach the session log the moment bench prints it — a
    # buffered pipe would lose everything if the session is killed while the
    # tunnel wedges mid-attempt (the exact scenario the bench survives)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "bench.py")] + args.bench_args,
        stdout=subprocess.PIPE, text=True, cwd=repo)
    record = None
    for line in proc.stdout:
        sys.stdout.write(line)
        sys.stdout.flush()
        if line.startswith("{"):
            try:
                record = json.loads(line)  # last parseable line wins
            except json.JSONDecodeError:
                pass
    proc.wait()
    kind = (record or {}).get("device_kind", "")
    if record and "tpu" in kind.lower().replace(" ", ""):
        out = os.path.join(here, f"bench_live_r{args.round}.json")
        stamp = time.strftime("%Y-%m-%d %H:%MZ", time.gmtime())
        with open(out, "w") as f:
            json.dump({
                "note": f"Live-tunnel window measurement, r{args.round} "
                        f"builder session {stamp}. Output of `python "
                        "bench.py` captured verbatim by "
                        "benchmarks/capture_live.py; the same command the "
                        "driver runs.",
                "record": record,
            }, f, indent=1)
        print(f"# live artifact written: {out}", file=sys.stderr)
    else:
        print(f"# no TPU record to capture (device_kind={kind!r})",
              file=sys.stderr)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
