#!/usr/bin/env python
"""Wall-clock to target test accuracy: D-PSGD vs MATCHA vs CHOCO.

BASELINE.json's metric has two clauses: gossip-steps/sec (bench.py) and
**wall-clock to target test-acc** — the quantity the MATCHA paper actually
optimizes (arXiv:1905.09435: same accuracy, less communication, therefore
less wall-clock per epoch on comm-bound clusters).  This harness measures the
second clause end-to-end on the current hardware: identical model/data/seeds,
three communication strategies, time to first reach a target accuracy.

Setup mirrors budget_sweep.py (ResNet-20, synthetic CIFAR-shaped clusters,
16 workers, zoo geometric graph id 2) so the two artifacts are comparable:

* ``dpsgd``       — FixedProcessor, all matchings every iteration (budget 1)
* ``matcha-0.5``  — MatchaProcessor at half the communication budget
* ``choco-0.5``   — same MATCHA schedule + top-k compression (keep 10%,
                    reference ratio 0.9, /root/reference/train_mpi.py:79)

For each run the artifact records the accuracy curve, the first epoch at
which the target is reached, cumulative wall-clock and cumulative
comm_time to that epoch (the recorder's two-program split, train/loop.py).

Run: ``python benchmarks/time_to_acc.py [--epochs E] [--target A] [--out P]``
(defaults sized for minutes on one TPU chip; CPU works too).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _miniature import miniature_config, ratio_range, timing_stats  # noqa: E402
from matcha_tpu.train import train  # noqa: E402

RUNS = (
    ("dpsgd", dict(matcha=False, budget=1.0)),
    ("matcha-0.5", dict(matcha=True, budget=0.5)),
    ("choco-0.5", dict(matcha=True, budget=0.5, communicator="choco",
                       compress_ratio=0.9, consensus_lr=0.3)),
    # the comm-bound regime: the skip backend pays per *active* matching
    # (lax.cond instead of masking), modeling the per-edge costs of the
    # paper's clusters / DCN hops — here the budget buys measurable time
    ("dpsgd-skip", dict(matcha=False, budget=1.0, gossip_backend="skip")),
    ("matcha-0.5-skip", dict(matcha=True, budget=0.5, gossip_backend="skip")),
)


def run_one(label: str, overrides: dict, epochs: int, target: float,
            reps: int = 2):
    """Run the config ``reps`` times: accuracy is deterministic (same seed,
    same backend — rep 0's curve is recorded), wall-clock is not, so every
    timing field carries its per-rep values and noise band (VERDICT r2
    item 7; the tunneled chip shows ±10-15% run-to-run)."""
    accs = None
    epoch_times_reps, comm_times_reps = [], []
    for rep in range(reps):
        cfg = miniature_config(
            f"time-to-acc-{label}", epochs,
            description="wall-clock to target test accuracy (BASELINE metric, clause 2)",
            **overrides,
        )
        hist = train(cfg).history
        if accs is None:
            accs = [float(h["test_acc_mean"]) for h in hist]
        epoch_times_reps.append([float(h["epoch_time"]) for h in hist])
        comm_times_reps.append([float(h["comm_time"]) for h in hist])

    reached = next((i for i, a in enumerate(accs) if a >= target), None)
    k = None if reached is None else reached + 1
    ttt = None if k is None else timing_stats(
        [sum(t[:k]) for t in epoch_times_reps])
    ctt = None if k is None else timing_stats(
        [sum(c[:k]) for c in comm_times_reps])
    epoch_mean = timing_stats(
        [sum(t) / len(t) for t in epoch_times_reps])
    comm_mean = timing_stats(
        [sum(c) / len(c) for c in comm_times_reps])
    record = {
        "run": label,
        "target_acc": target,
        "reps": reps,
        "reached": reached is not None,
        "epochs_to_target": k,
        "time_to_target_s": None if ttt is None else ttt["mean"],
        "time_to_target_stats": ttt,
        "comm_time_to_target_s": None if ctt is None else ctt["mean"],
        "comm_time_to_target_stats": ctt,
        "final_test_acc": round(accs[-1], 4),
        "mean_epoch_time_s": epoch_mean["mean"],
        "mean_epoch_time_stats": epoch_mean,
        "mean_comm_time_s": comm_mean["mean"],
        "comm_share": round(comm_mean["mean"] / max(epoch_mean["mean"], 1e-9), 4),
        "test_acc_curve": [round(a, 4) for a in accs],
    }
    print(json.dumps(record), flush=True)
    return record


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--target", type=float, default=0.97)
    p.add_argument("--reps", type=int, default=2,
                   help="timing repetitions per config (noise band)")
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "time_to_acc.json"))
    args = p.parse_args()

    runs = [run_one(label, dict(ov), args.epochs, args.target, reps=args.reps)
            for label, ov in RUNS]

    by = {r["run"]: r for r in runs}
    summary = {
        "experiment": "wall-clock to target test accuracy "
                      "(ResNet-20, synthetic CIFAR shapes, 16 workers, graphid 2)",
        "target_acc": args.target,
        "epochs": args.epochs,
        "reps": args.reps,
        "runs": runs,
    }
    d, m = by.get("dpsgd"), by.get("matcha-0.5")
    if d and m and d["reached"] and m["reached"]:
        # the paper's economy: same target, fraction of the communication;
        # each ratio carries its cross-rep range — a claim inside the band
        # is noise, not a speedup
        summary["matcha_comm_time_ratio_vs_dpsgd"] = round(
            m["comm_time_to_target_s"] / max(d["comm_time_to_target_s"], 1e-9), 3)
        summary["matcha_comm_time_ratio_range"] = ratio_range(
            m["comm_time_to_target_stats"]["reps"],
            d["comm_time_to_target_stats"]["reps"])
        summary["matcha_wall_clock_ratio_vs_dpsgd"] = round(
            m["time_to_target_s"] / max(d["time_to_target_s"], 1e-9), 3)
        summary["matcha_wall_clock_ratio_range"] = ratio_range(
            m["time_to_target_stats"]["reps"],
            d["time_to_target_stats"]["reps"])
        # Context the ratios need: MATCHA's wall-clock economy presumes
        # communication dominates the iteration (the reference's MPI world,
        # where gossip is pickled host-memory sendrecv).  On this backend the
        # gossip chain is a fused on-chip program and comm_share is ~1-2%, so
        # wall-clock-to-target tracks *epochs*-to-target and a lower budget
        # only trades convergence speed for savings on an already-negligible
        # cost.  The budget knob matters again when the worker axis spans
        # hosts (DCN) — parallel/multihost.py — or for the reference's own
        # execution model; the single-chip artifact records the comm_share
        # that makes this explicit rather than claiming a speedup.
        summary["dpsgd_comm_share"] = d["comm_share"]
        summary["note"] = (
            "comm_share ~0.01-0.02 on one TPU chip: the fused gossip backend "
            "makes communication nearly free, so time-to-target follows "
            "epochs-to-target; MATCHA's budget economy targets comm-bound "
            "(multi-host/MPI) regimes, which this backend has designed away "
            "at single-chip scale"
        )
    ds, ms = by.get("dpsgd-skip"), by.get("matcha-0.5-skip")
    if ds and ms and ds["reached"] and ms["reached"]:
        # NOTE: the two-program comm timer cannot attribute the skip
        # backend's effect (the cond cost/saving lands inside the train
        # step, not the isolated gossip chain) — the per-step mechanism is
        # pinned by benchmarks/skip_microbench.py; this records the
        # end-to-end outcome only
        summary["skip_backend_wall_clock_ratio"] = round(
            ms["time_to_target_s"] / max(ds["time_to_target_s"], 1e-9), 3)
        summary["skip_backend_wall_clock_ratio_range"] = ratio_range(
            ms["time_to_target_stats"]["reps"],
            ds["time_to_target_stats"]["reps"])
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
