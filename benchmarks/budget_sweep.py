#!/usr/bin/env python
"""The paper's headline experiment in miniature: MATCHA budget sweep vs D-PSGD.

MATCHA's claim (/root/reference/README.md:4-5, arXiv:1905.09435) is that
activating a *fraction* of the matchings per iteration — budget cb < 1 —
matches full-graph D-PSGD accuracy while spending a fraction of the
communication.  This harness reproduces that comparison end-to-end in this
framework: ResNet-20 on synthetic CIFAR-shaped data, 16 workers on the zoo
geometric graph (graphid 2), MATCHA at budgets {0.1, 0.25, 0.5, 1.0} against
the D-PSGD baseline (FixedProcessor, all matchings every step).

Emits one JSON line per run plus a final summary table artifact
(``budget_sweep.json`` next to this file, committed) mapping budget →
{final test accuracy, mean comm_time/epoch, measured comm fraction}.

Run: ``python benchmarks/budget_sweep.py [--epochs E] [--out PATH]``
(defaults sized to finish in minutes on one TPU chip; CPU works too).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _miniature import miniature_config, timing_stats  # noqa: E402
from matcha_tpu.plan import plan_candidate  # noqa: E402
from matcha_tpu.topology import graph_size, select_graph  # noqa: E402
from matcha_tpu.train import train  # noqa: E402

BUDGETS = (0.1, 0.25, 0.5, 1.0)
GRAPHID = 2  # the zoo geometric graph every miniature run uses


def predicted_columns(budget: float, seed: int = 1) -> dict:
    """The planner's offline prediction for one sweep point — attached to
    each measured record so the artifact carries predicted-vs-measured
    side by side (the planner's falsifiability hook; tests/test_plan.py
    checks the ranking against the committed table)."""
    cand = plan_candidate(
        select_graph(GRAPHID), graph_size(GRAPHID), budget, seed=seed,
        mc_trials=4, mc_steps=60)
    return {
        "rho": round(cand["rho"], 6),
        "mc_empirical_rate": round(cand["mc_empirical_rate"], 6),
        "steps_to_target": None if cand["steps_to_target"] is None
        else round(cand["steps_to_target"], 2),
        "expected_comm_fraction": round(cand["expected_comm_fraction"], 4),
        "expected_comm_units": cand["expected_comm_units"],
    }


def run_one(label: str, epochs: int, *, matcha: bool, budget: float = 1.0,
            reps: int = 2):
    """Accuracy is deterministic (same seed/backend; rep 0's curve is
    recorded); wall-clock is not — timing fields carry per-rep values and
    the noise band (VERDICT r2 item 7)."""
    accs = None
    comm_means, epoch_means = [], []
    for rep in range(reps):
        cfg = miniature_config(
            f"budget-sweep-{label}", epochs,
            description="MATCHA budget sweep vs D-PSGD (paper headline, miniature)",
            matcha=matcha, budget=budget, communicator="decen",
        )
        hist = train(cfg).history
        if accs is None:
            accs = [h["test_acc_mean"] for h in hist]
        comm_means.append(float(np.mean([h["comm_time"] for h in hist])))
        epoch_means.append(float(np.mean([h["epoch_time"] for h in hist])))
    comm_stats, epoch_stats = timing_stats(comm_means), timing_stats(epoch_means)
    record = {
        "run": label,
        "budget": budget if matcha else 1.0,
        "algorithm": "matcha" if matcha else "dpsgd",
        "reps": reps,
        "final_test_acc": round(float(accs[-1]), 4),
        "best_test_acc": round(float(max(accs)), 4),
        "mean_comm_time_per_epoch": comm_stats["mean"],
        "mean_comm_time_stats": comm_stats,
        "mean_epoch_time": epoch_stats["mean"],
        "mean_epoch_time_stats": epoch_stats,
        "test_acc_curve": [round(float(a), 4) for a in accs],
    }
    record["comm_fraction"] = round(
        record["mean_comm_time_per_epoch"] / max(record["mean_epoch_time"], 1e-9), 4)
    if matcha:
        record["predicted"] = predicted_columns(budget)
    print(json.dumps(record), flush=True)
    return record


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--reps", type=int, default=2,
                   help="timing repetitions per config (noise band)")
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "budget_sweep.json"))
    args = p.parse_args()

    runs = [run_one("dpsgd", args.epochs, matcha=False, reps=args.reps)]
    for b in BUDGETS:
        runs.append(run_one(f"matcha-{b}", args.epochs, matcha=True, budget=b,
                            reps=args.reps))

    dpsgd_acc = runs[0]["final_test_acc"]
    # predicted-vs-measured ordering: the planner's iteration count to the
    # consensus target against the measured epochs-to-0.9-accuracy
    matcha_runs = [r for r in runs if r["algorithm"] == "matcha"]
    predicted_rank = [r["budget"] for r in sorted(
        matcha_runs,
        key=lambda r: (float("inf")
                       if r["predicted"]["steps_to_target"] is None
                       else r["predicted"]["steps_to_target"]))]
    measured_rank = [r["budget"] for r in sorted(
        matcha_runs,
        key=lambda r: next(
            (i for i, a in enumerate(r["test_acc_curve"]) if a >= 0.9),
            len(r["test_acc_curve"])))]
    summary = {
        "experiment": "MATCHA budget sweep vs D-PSGD "
                      "(ResNet-20, synthetic CIFAR shapes, 16 workers, graphid 2)",
        "epochs": args.epochs,
        "reps": args.reps,
        "dpsgd_final_test_acc": dpsgd_acc,
        "runs": runs,
        # the paper's claim, checked at the sweep point the VERDICT names:
        # MATCHA at budget <= 0.5 stays within a couple points of D-PSGD
        "matcha_at_half_budget_vs_dpsgd": round(
            next(r["final_test_acc"] for r in runs
                 if r["algorithm"] == "matcha" and r["budget"] == 0.5) - dpsgd_acc,
            4),
        # planner cross-check (matcha_tpu.plan): budgets ordered by
        # predicted steps-to-consensus vs by measured epochs-to-0.9 — the
        # sweep now carries its own prediction audit trail
        "predicted_rank_by_budget": predicted_rank,
        "measured_rank_by_budget": measured_rank,
    }
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
