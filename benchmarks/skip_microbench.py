#!/usr/bin/env python
"""Per-step gossip rate: masked backend vs the cond-skipping backend.

The masked backends (`gather`/`dense`/`fused`) execute every matching every
step and mask inactive ones to zero — the budget changes arithmetic, not
time.  The `skip` backend wraps each matching in ``lax.cond`` so inactive
matchings cost nothing at runtime.  This microbench measures that directly:
the same 16-worker, ResNet-20-sized gossip chain under a full D-PSGD
schedule (all matchings active) and a MATCHA budget-0.5 schedule (~half
active in expectation), on both backends.

This is the evidence behind the claims in README.md / docs/MULTIHOST.md —
including two honest ceilings.  (1) ``lax.cond``'s identity branch still
writes a full-state buffer, so on-chip the saving exists only while
per-matching *work* exceeds a state copy: at ResNet-18-ImageNet size the
chain is copy-bound and skip saves nothing (committed artifact, config 2).
(2) At ResNet-20 size the budget-0.5 schedule measures ~1.2× faster on
skip, but the masked control measured 1.06× and 1.16× on two runs of the
tunneled chip — the run-to-run noise is comparable to the marginal gain, so
the committed numbers show the *direction*, not a precise on-chip speedup.
The regime the backend is actually for is the sharded one, where the
skipped cost is a cross-chip/DCN collective, not arithmetic
(``shard_map_gossip_fn(skip=True)``; semantics validated on the virtual
mesh, payoff measurable only on pod fabric).  Committed result:
``skip_microbench.json``.

Run: ``python benchmarks/skip_microbench.py [--workers N] [--steps T]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ResNet-20/CIFAR-10 flat parameter count (bench.py computes it from the
# model; hardcoded here so the microbench never touches the model zoo)
RESNET20_DIM = 273_258


def time_chain(comm, x, flags, steps):
    import jax
    import jax.numpy as jnp

    # forced readback serializes the whole chain (see bench.py: on tunneled
    # backends block_until_ready can return early and inflate rates 100x+)
    run = jax.jit(lambda x: jnp.sum(comm.run(x, flags)[0][:, :8]))
    float(run(x))
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        float(run(x))
        best = min(best, time.perf_counter() - t0)
    return steps / best


def measure(workers: int, dim: int, steps: int) -> dict:
    import jax.numpy as jnp

    from matcha_tpu import topology as tp
    from matcha_tpu.communicator import make_decen
    from matcha_tpu.schedule import fixed_schedule, matcha_schedule

    # the paper's 16-node geometric zoo graph at the default size; a
    # same-family generated graph for any other --workers
    edges = (tp.select_graph(2) if workers == 16
             else tp.make_graph("geometric", workers, seed=1))
    scheds = {
        "dpsgd": fixed_schedule(edges, workers, iterations=steps),
        "matcha-0.5": matcha_schedule(edges, workers,
                                      iterations=steps, budget=0.5, seed=1),
    }
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(workers, dim)).astype(np.float32))

    result = {"workers": workers, "dim": dim, "steps": steps, "rates": {}}
    for sname, sched in scheds.items():
        flags = jnp.asarray(sched.flags, jnp.float32)
        result.setdefault("mean_active_matchings", {})[sname] = round(
            float(flags.sum(axis=1).mean()), 2)
        for backend in ("gather", "skip"):
            comm = make_decen(sched, backend=backend)
            rate = time_chain(comm, x, flags, steps)
            result["rates"][f"{sname}/{backend}"] = round(rate, 1)

    r = result["rates"]
    result["masked_speedup_at_half_budget"] = round(
        r["matcha-0.5/gather"] / r["dpsgd/gather"], 2)
    result["skip_speedup_at_half_budget"] = round(
        r["matcha-0.5/skip"] / r["dpsgd/skip"], 2)
    return result


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--workers", type=int, default=16)
    # long chains amortize the ~70 ms tunnel dispatch; short ones put the
    # run-to-run noise at ±10-15%, swamping the effect being measured
    p.add_argument("--steps", type=int, default=128)
    p.add_argument("--dim", type=int, default=RESNET20_DIM)
    # second size showing the cond identity-copy ceiling (ResNet-18/ImageNet
    # param count); 0 disables
    p.add_argument("--dim2", type=int, default=11_173_962)
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "skip_microbench.json"))
    args = p.parse_args()

    configs = [measure(args.workers, args.dim, args.steps)]
    if args.dim2:
        # the big-dim config runs ~36 ms/step; a short chain suffices (it is
        # bound by full-state traffic, not dispatch)
        configs.append(measure(args.workers, args.dim2, max(8, args.steps // 4)))
    result = {
        "experiment": "per-step gossip rate, masked vs cond-skipping backend",
        "configs": configs,
        "note": "skip pays only while per-matching work exceeds a full-state "
                "copy (the cond identity branch writes one); at the larger "
                "dim the chain is copy-bound and the budget buys nothing "
                "on-chip — the sharded skip path targets the regime where "
                "the avoided cost is a cross-chip collective instead",
    }
    print(json.dumps(result))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
