#!/bin/sh
# Wait for the intermittent axon TPU tunnel to come alive, then run the
# prioritized measurement session (tpu_session.sh) exactly once.  Probing is
# cheap (a bounded jax.devices() call); the poll interval keeps a dead-tunnel
# loop from hammering backend init.  Usage from the repo root:
#     sh benchmarks/tpu_watch.sh [max_polls]
# Exit code is tpu_session.sh's, or 3 if the tunnel never came up.
MAX_POLLS=${1:-40}
i=0
while :; do
    if timeout 90 python -c "import jax; k = jax.devices()[0].device_kind; assert 'tpu' in k.lower(), k" 2>/dev/null; then
        echo "tunnel alive (poll $i) — starting tpu_session.sh"
        exec sh benchmarks/tpu_session.sh
    fi
    i=$((i + 1))
    [ "$i" -ge "$MAX_POLLS" ] && break
    sleep 180  # only between probes — no trailing sleep after the last one
done
echo "tunnel never came alive after $MAX_POLLS polls"
exit 3
