#!/usr/bin/env python
"""A/B harness: the permutation-form kernel vs the dense fused kernel.

Since ISSUE 13 the perm form is a **production backend**
(``matcha_tpu.parallel.perm_gossip_run`` — ``gossip_backend="perm"``), and
this probe re-exports it instead of carrying its own copy: there is exactly
one perm kernel in the repo, and the A/B below times the same program text
training runs.  The dense side is likewise the production fused W-stack
kernel (``fused_gossip_run``).  What remains probe-shaped is the protocol:

* Both forms run bf16 in/out with f32 accumulate — the production fused
  kernel's dtypes (bench.py default) — so the dense baseline streams
  exactly the bytes it streams in production.
* Correctness is checked on device against the dense form in f32 and GATES
  the ratio: outputs that diverge beyond rounding drift mark the record
  inconclusive and withhold the ratio (a silently mis-lowered gather must
  not trigger integration).  The f32 gate avoids bf16's percent-scale
  chain drift, which would blind it; a mis-lowered gather is
  dtype-independent and O(1) off.
* Writes one JSON record to --out; exits 0 even when inconclusive.  Run on
  a live tunnel (tpu_session.sh, after the headline steps); ``--smoke``
  pins CPU for an off-tunnel interpret-mode correctness check.

The hardware question it measures — can M VPU row-shuffles beat one MXU
matmul once the W stream is gone? — feeds the
``plan.cost.choose_gossip_backend`` gate together with the roofline's
measured-vs-ceiling ratio (``obs_tpu.py roofline --backend both``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

N, D, T, BD, W, M = 256, 273258, 2000, 4096, 8, 10
ALPHA = 0.37  # representative mixing weight; any fixed value works


def random_involutions(rng, m: int, n: int) -> np.ndarray:
    """M random involutions with fixed points (matching structure)."""
    perms = np.empty((m, n), np.int64)
    for j in range(m):
        pi = np.arange(n)
        pairs = rng.permutation(n)[: 2 * (n // 3)].reshape(-1, 2)
        pi[pairs[:, 0]], pi[pairs[:, 1]] = pairs[:, 1], pairs[:, 0]
        perms[j] = pi
    return perms


def laplacians_from_involutions(perms: np.ndarray,
                                partnered: np.ndarray) -> np.ndarray:
    """``L_j = D_j − A_j`` for each involution — what build_mixing_stack
    composes into the dense W stack (the same W the perm form applies)."""
    m, n = perms.shape
    L = np.zeros((m, n, n), np.float32)
    rows = np.arange(n)
    for j in range(m):
        L[j, rows, rows] = partnered[j]
        on = partnered[j] > 0
        L[j, rows[on], perms[j][on]] -= 1.0
    return L


def main() -> int:
    global N, D, T, BD, W, M
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes for a CPU correctness check")
    args = p.parse_args()
    if args.reps < 1:
        p.error("--reps must be >= 1")
    if args.smoke:
        N, D, T, BD, W, M = 16, 1024, 32, 512, 4, 4

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from matcha_tpu.utils import pin_platform

    # --smoke is the off-tunnel correctness check: pin CPU before backend
    # init or the env's default (tunneled TPU) backend hangs when down
    pin_platform("cpu" if args.smoke else None)
    import jax
    import jax.numpy as jnp

    from matcha_tpu.parallel import (
        build_mixing_stack,
        fused_gossip_run,
        involution_tables,
        perm_gossip_run,
    )

    rng = np.random.default_rng(0)
    perms, partnered = involution_tables(random_involutions(rng, M, N))
    laplacians = laplacians_from_involutions(perms, partnered)
    # Bernoulli flag stream at the MATCHA-0.5-like activation rate
    flags = (rng.random((T, M)) < 0.5).astype(np.float32)

    @jax.jit
    def gen_x():
        # bf16 state: the production kernels' wire dtype (bench.py
        # default) — the dense baseline must stream the same bytes it
        # really streams, or the perm/dense ratio is biased
        return jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.bfloat16)

    x = gen_x()
    jax.block_until_ready(x)
    weights_d = jnp.asarray(ALPHA * flags, jnp.float32)  # [T, M] stream

    interp = jax.devices()[0].platform == "cpu"  # CPU: interpret-mode only

    def run_dense(x, stk):
        return fused_gossip_run(x, stk, block_d=BD, w_window=W,
                                interpret=interp)

    def run_perm(x, weights):
        return perm_gossip_run(x, weights, perms, partnered, block_d=BD,
                               w_window=W, interpret=interp)

    def rate(fn, *a):
        g = jax.jit(lambda *a: jnp.sum(fn(*a)[:, :8].astype(jnp.float32)))
        float(g(*a))  # compile + warm, forced readback (tunneled-TPU rule)
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(g(*a))
            best = min(best, time.perf_counter() - t0)
        return T / best

    rec = {"probe": "perm-vs-dense-fused", "n": N, "d": D, "steps": T,
           "block_d": BD, "w_window": W, "matchings": M,
           "kernel": "matcha_tpu.parallel.perm_gossip_run",  # the ONE copy
           "device_kind": jax.devices()[0].device_kind}
    if args.smoke:
        # interpret-mode numbers are correctness evidence only — a smoke
        # record must never impersonate hardware in the session artifact
        rec["smoke_interpret_mode"] = True
    try:
        stack32 = build_mixing_stack(laplacians, ALPHA, flags, jnp.float32)
        jax.block_until_ready(stack32)
        # Correctness gate in f32 (same lowering path, no per-step rounding
        # divergence).  Dense composes W_t from the SAME involutions the
        # perm form gathers through, so agreement here is a proof about
        # the lowering, not the math.
        y_dense = run_dense(x.astype(jnp.float32), stack32)
        y_perm = run_perm(x.astype(jnp.float32), weights_d)
        err = float(jnp.max(jnp.abs(y_perm - y_dense))
                    / (jnp.max(jnp.abs(y_dense)) + 1e-30))
        rec["rel_err_vs_dense_f32"] = err
        rec["valid"] = err < 1e-3
        # Rates in the production dtypes: bf16 state/stack, f32 accumulate
        rec["dense_steps_per_sec"] = round(
            rate(run_dense, x, stack32.astype(jnp.bfloat16)), 1)
        rec["perm_steps_per_sec"] = round(rate(run_perm, x, weights_d), 1)
        if not rec["valid"]:
            rec["inconclusive"] = "f32 outputs diverge; ratio withheld"
        elif args.smoke:
            rec["inconclusive"] = ("interpret-mode timing is meaningless; "
                                   "ratio withheld (correctness gate only)")
        else:
            rec["ratio"] = round(rec["perm_steps_per_sec"]
                                 / rec["dense_steps_per_sec"], 4)
    except Exception as e:  # noqa: BLE001 — the artifact records the failure
        rec["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
