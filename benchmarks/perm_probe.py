#!/usr/bin/env python
"""Hardware probe: can the permutation form of W_t beat the dense MXU form?

The fused kernel executes each gossip step as a dense ``W_t @ x`` on the MXU
and streams the precomputed ``[T, N, N]`` W stack from HBM — that stream is
the dominant HBM term of the per-step roofline (benchmarks/ROOFLINE.md).
But W_t is structurally ``I − α·Σ_j flag[t,j]·L_j`` over perfect matchings,
i.e. per row: ``(W_t x)_i = (1 − α·deg_i,t)·x_i + α·Σ_j flag[t,j]·x_{π_j(i)}``
with the involutions π_j *static*.  The permutation form therefore needs only
the ``[T, M]`` flag stream from HBM (≈2,000× smaller) and replaces the MXU
dot with M static row-shuffles + weighted adds on the VPU.

Whether that wins is a pure hardware-scheduling question: the shuffle of a
VMEM-resident ``[N, block_d]`` block is sublane data movement whose cost
Mosaic decides, and the VPU flops (≈(M+2)·N·bd) are ~60× fewer than the
MXU's 2·N²·bd but run on a ~50× slower unit.  So: measure, don't assume.

Both forms run bf16 in/out with f32 accumulate — the production fused
kernel's dtypes (bench.py default) — so the dense baseline streams exactly
the bytes it streams in production.  Correctness is checked on device
against the dense form and GATES the ratio: outputs that diverge beyond
bf16 rounding drift mark the record inconclusive and withhold the ratio
(a silently mis-lowered gather must not trigger integration).  Writes one
JSON record to --out; exits 0 even when inconclusive.  Run on a live
tunnel (tpu_session.sh, after the headline steps); `--smoke` pins CPU for
an off-tunnel correctness check in interpret mode.

Models the hot path of /root/reference/communicator.py:92-122 like bench.py;
integrate as a gossip backend only if this measures a clear win.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

N, D, T, BD, W, M = 256, 273258, 2000, 4096, 8, 10
ALPHA = 0.37  # representative mixing weight; any fixed value works


def main() -> int:
    global N, D, T, BD, W, M
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes for a CPU correctness check")
    args = p.parse_args()
    if args.reps < 1:
        p.error("--reps must be >= 1")
    if args.smoke:
        N, D, T, BD, W, M = 16, 1024, 32, 512, 4, 4

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from matcha_tpu.utils import pin_platform

    # --smoke is the off-tunnel correctness check: pin CPU before backend
    # init or the env's default (tunneled TPU) backend hangs when down
    pin_platform("cpu" if args.smoke else None)
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    rng = np.random.default_rng(0)
    # M random involutions with fixed points (matching structure) + a
    # Bernoulli flag stream at the MATCHA-0.5-like activation rate
    perms = np.empty((M, N), np.int32)
    for j in range(M):
        pi = np.arange(N)
        pairs = rng.permutation(N)[: 2 * (N // 3)].reshape(-1, 2)
        pi[pairs[:, 0]], pi[pairs[:, 1]] = pairs[:, 1], pairs[:, 0]
        perms[j] = pi
    partnered = (perms != np.arange(N)[None, :]).astype(np.float32)  # [M, N]
    flags = (rng.random((T, M)) < 0.5).astype(np.float32)

    @jax.jit
    def gen_x():
        # bf16 state: the production fused kernel's wire dtype (bench.py
        # default) — the dense baseline must stream the same bytes it
        # really streams, or the perm/dense ratio is biased
        return jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.bfloat16)

    x = gen_x()
    jax.block_until_ready(x)
    flags_d = jnp.asarray(flags)
    partnered_d = jnp.asarray(partnered)

    # --- dense reference: per-step W_t @ x via the W stack (MXU form) ------
    @jax.jit
    def build_w_stack():
        eye = jnp.eye(N, dtype=jnp.float32)
        deg = flags_d @ partnered_d  # [T, N]
        w = (1.0 - ALPHA * deg)[:, :, None] * eye[None]
        onehot = jax.nn.one_hot(jnp.asarray(perms), N, dtype=jnp.float32)
        # rows i with partner p get α at column p (fixed points already have
        # their α·x_i folded into the diagonal term via deg=0)
        for j in range(M):
            w = w + (ALPHA * flags_d[:, j])[:, None, None] * (
                partnered_d[j][None, :, None] * onehot[j][None])
        return w  # f32; cast per use

    def dense_kernel(x_ref, w_ref, o_ref):
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _():
            o_ref[...] = x_ref[...]

        for k in range(W):
            o_ref[...] = jnp.dot(
                w_ref[k], o_ref[...],
                preferred_element_type=jnp.float32).astype(o_ref.dtype)
        # (bf16 in/out, f32 accumulate — identical to pallas_gossip)

    interp = jax.devices()[0].platform == "cpu"  # CPU: interpret-mode only

    @jax.jit
    def run_dense(x, stk):
        return pl.pallas_call(
            dense_kernel, grid=(pl.cdiv(D, BD), T // W), interpret=interp,
            in_specs=[pl.BlockSpec((N, BD), lambda i, t: (0, i)),
                      pl.BlockSpec((W, N, N), lambda i, t: (t, 0, 0))],
            out_specs=pl.BlockSpec((N, BD), lambda i, t: (0, i)),
            out_shape=jax.ShapeDtypeStruct((N, D), x.dtype))(x, stk)

    # --- permutation form: flags stream only, row gathers in VMEM ---------
    # perms/partnered ride as (replicated-block) kernel inputs: Pallas
    # forbids captured array constants, and as refs the gathers are traced
    perms_d = jnp.asarray(perms, jnp.int32)  # [M, N]

    def perm_kernel(x_ref, f_ref, pi_ref, pr_ref, o_ref):
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _():
            o_ref[...] = x_ref[...]

        pr = pr_ref[...]  # [M, N]
        for k in range(W):
            fk = f_ref[k]  # [M]
            cur = o_ref[...].astype(jnp.float32)  # f32 accumulate, bf16 store
            deg = fk @ pr  # [N]
            acc = (1.0 - ALPHA * deg)[:, None] * cur
            for j in range(M):
                # row gather: partner rows of this matching (π_j involution)
                g = jnp.take(cur, pi_ref[j], axis=0)
                acc = acc + (ALPHA * fk[j] * pr[j])[:, None] * g
            o_ref[...] = acc.astype(o_ref.dtype)

    @jax.jit
    def run_perm(x, flags):
        return pl.pallas_call(
            perm_kernel, grid=(pl.cdiv(D, BD), T // W), interpret=interp,
            in_specs=[pl.BlockSpec((N, BD), lambda i, t: (0, i)),
                      pl.BlockSpec((W, M), lambda i, t: (t, 0)),
                      pl.BlockSpec((M, N), lambda i, t: (0, 0)),
                      pl.BlockSpec((M, N), lambda i, t: (0, 0))],
            out_specs=pl.BlockSpec((N, BD), lambda i, t: (0, i)),
            out_shape=jax.ShapeDtypeStruct((N, D), x.dtype))(
                x, flags, perms_d, partnered_d)

    def rate(fn, *a):
        g = jax.jit(lambda *a: jnp.sum(fn(*a)[:, :8].astype(jnp.float32)))
        float(g(*a))  # compile + warm, forced readback (tunneled-TPU rule)
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(g(*a))
            best = min(best, time.perf_counter() - t0)
        return T / best

    rec = {"probe": "perm-vs-dense-fused", "n": N, "d": D, "steps": T,
           "block_d": BD, "w_window": W, "matchings": M,
           "device_kind": jax.devices()[0].device_kind}
    if args.smoke:
        # interpret-mode numbers are correctness evidence only — a smoke
        # record must never impersonate hardware in the session artifact
        rec["smoke_interpret_mode"] = True
    try:
        stk = build_w_stack()  # f32
        jax.block_until_ready(stk)
        # Correctness gate in f32 (same lowering path, no per-step rounding
        # divergence — bf16's 8-bit mantissa drifts percent-scale over the
        # chain even when both kernels are right, which would blind the
        # gate).  A mis-lowered gather is dtype-independent and O(1) off.
        y_dense = run_dense(x.astype(jnp.float32), stk)
        y_perm = run_perm(x.astype(jnp.float32), flags_d)
        err = float(jnp.max(jnp.abs(y_perm - y_dense))
                    / (jnp.max(jnp.abs(y_dense)) + 1e-30))
        rec["rel_err_vs_dense_f32"] = err
        rec["valid"] = err < 1e-3
        # Rates in the production dtypes: bf16 state/stack, f32 accumulate
        rec["dense_steps_per_sec"] = round(
            rate(run_dense, x, stk.astype(jnp.bfloat16)), 1)
        rec["perm_steps_per_sec"] = round(rate(run_perm, x, flags_d), 1)
        if not rec["valid"]:
            rec["inconclusive"] = "f32 outputs diverge; ratio withheld"
        elif args.smoke:
            rec["inconclusive"] = ("interpret-mode timing is meaningless; "
                                   "ratio withheld (correctness gate only)")
        else:
            rec["ratio"] = round(rec["perm_steps_per_sec"]
                                 / rec["dense_steps_per_sec"], 4)
    except Exception as e:  # noqa: BLE001 — the artifact records the failure
        rec["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
