#!/usr/bin/env python
"""Full-train-step throughput at the north-star configuration.

BASELINE.json's north star is worded as a *training run*: 256 virtual
workers, ResNet-20/CIFAR-10, MATCHA budget 0.5, one gossip step per SGD step
(/root/reference/train_mpi.py:113-145 — the loop this framework compiles
into a single program).  bench.py isolates the gossip chain; this harness
measures the quantity the wording implies — `make_train_step` steps/sec with
the gossip mix fused into the compiled step — plus the **marginal cost of
gossip** obtained by differencing against an identical run with
`communicator="none"`, and the roofline argument that connects the two:

    per train step, fwd+bwd ≈ 3 × 2 × B_total × F_model FLOPs
    gossip adds 2·N²·D FLOPs (the dense W_t @ x mix)

At N=256, B=32/worker, ResNet-20 (F ≈ 41 MFLOP/image, D = 273k):
fwd+bwd ≈ 2.0 TFLOP vs gossip 35.8 GFLOP — gossip is ~1.8% of the step's
FLOPs, so a MATCHA budget's saving on-chip is bounded by that share (the
budget economy targets comm-bound fabrics; see README Performance).

Run: ``python benchmarks/train_step_bench.py [--workers N] [--batch B]
[--steps K] [--reps R] [--platform cpu|tpu] [--out PATH]``
(CPU note: one step at the full config is ~2 TFLOP — pass
``--workers 16 --batch 4`` for a CPU smoke.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(args) -> dict:
    import jax
    import jax.numpy as jnp

    from matcha_tpu import topology as tp
    from matcha_tpu.communicator import select_communicator
    from matcha_tpu.models import select_model
    from matcha_tpu.schedule import matcha_schedule
    from matcha_tpu.train import make_lr_schedule
    from matcha_tpu.train.state import init_train_state, make_optimizer, make_train_step

    n, b = args.workers, args.batch
    hw = args.image_size
    # dataset name only routes the zoo's variant choice: any 224 image size
    # picks the ImageNet 4-stage variant for 'res*' names
    model = select_model(args.model, "imagenet" if hw >= 64 else "cifar10",
                         num_classes=args.classes, remat=args.remat)
    print(f"# [{time.strftime('%H:%M:%S')}] building {n}-worker schedule "
          f"(CVX solve ~60-90s at 256)...", file=sys.stderr, flush=True)
    edges = tp.make_graph("geometric", n, seed=1)
    dec = tp.decompose(edges, n, seed=1)
    # every chain_j(state) rep restarts from the same initial state (and
    # therefore step 0), so only rows [0, steps) of the flag stream are read
    sched = matcha_schedule(dec, n, iterations=args.steps + 1,
                            budget=0.5, seed=0)
    lr = make_lr_schedule(0.1, batches_per_epoch=100, warmup=False)
    optimizer = make_optimizer(lr)

    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.normal(size=(n, b, hw, hw, 3)).astype(np.float32))
    yb = jnp.asarray(rng.integers(0, args.classes, size=(n, b)).astype(np.int32))
    key = jax.random.PRNGKey(0)
    # flat parameter count, from shapes only (no init program on the tunnel)
    var_shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, hw, hw, 3)), train=False),
        jax.random.PRNGKey(0))
    d = sum(int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(var_shapes["params"]))

    def log(msg):
        # stage-by-stage wall-clock breadcrumbs on stderr: a timed-out
        # tunneled run must show WHERE the budget went (transfer? init
        # compile? chain compile?) instead of dying silently
        print(f"# [{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
              flush=True)

    def steps_per_sec(comm_name: str) -> float:
        comm = select_communicator(comm_name, sched)
        log(f"{comm_name}: init_train_state...")
        state, flattener = init_train_state(
            model, (hw, hw, 3), n, optimizer, comm, seed=0)
        jax.block_until_ready(state.params)
        log(f"{comm_name}: init done; compiling {args.steps}-step chain...")
        step = make_train_step(model, optimizer, comm, flattener, sched.flags,
                               lr_schedule=lr,
                               grad_chunk=args.grad_chunk or None)

        def chain(state):
            for _ in range(args.steps):  # unrolled; step count is small
                state, m = step(state, xb, yb, key)
            return state, m

        chain_j = jax.jit(chain)
        # force completion through a scalar readback (tunneled-TPU rule:
        # block_until_ready alone can return early — see bench.py)
        out_state, m = chain_j(state)
        float(m["loss"])
        log(f"{comm_name}: chain compiled + warm; timing {args.reps} reps...")
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            _, m = chain_j(state)
            float(m["loss"])
            best = min(best, time.perf_counter() - t0)
        log(f"{comm_name}: {args.steps / best:.2f} steps/s")
        return args.steps / best

    log(f"data on device: x {xb.shape} {xb.nbytes >> 20} MiB...")
    jax.block_until_ready(xb)
    log("data transferred; schedule built")
    rate_full = steps_per_sec("decen")
    rate_none = steps_per_sec("none")

    # per-image forward FLOPs at the canonical sizes; off-canonical image
    # sizes scale ~quadratically with the spatial area.  Models without a
    # table entry get NO fwd/bwd roofline numbers (omitting beats emitting
    # a confidently-wrong gossip_flop_share of 1.0).
    canon = {"resnet20": (32, 41.0e6), "resnet50": (224, 4.1e9)}
    base = canon.get(args.model.lower())
    f_img = base[1] * (hw / base[0]) ** 2 if base else None
    flops_fwd_bwd = 3 * 2 * n * b * f_img if f_img else None  # fwd + ~2x bwd
    flops_gossip = 2.0 * n * n * d
    record = {
        "metric": f"train-steps/sec @ {n} workers x batch {b}, "
                  f"{args.model}@{hw}px, "
                  f"MATCHA budget 0.5 (gossip fused into the step)",
        "value": round(rate_full, 3),
        "unit": "train_steps_per_sec",
        "train_steps_per_sec_no_comm": round(rate_none, 3),
        "gossip_marginal_frac": round(
            max(0.0, 1.0 - rate_full / max(rate_none, 1e-9)), 4),
        "roofline": {
            **({"flops_fwd_bwd_per_step": flops_fwd_bwd,
                "gossip_flop_share": round(
                    flops_gossip / (flops_gossip + flops_fwd_bwd), 4)}
               if flops_fwd_bwd else
               {"note_fwd_bwd": f"no canonical FLOP table entry for "
                                f"{args.model}; fwd/bwd share omitted"}),
            "flops_gossip_per_step": flops_gossip,
            "note": "gossip-steps/sec in a training run == train-steps/sec; "
                    "the isolated gossip kernel rate (bench.py value) bounds "
                    "the comm term, and the FLOP share bounds what any "
                    "budget<1 can save on-chip",
        },
        "workers": n, "batch": b, "model": args.model,
        "image_size": hw, "flat_dim": d,
        "steps": args.steps, "reps": args.reps,
        "remat": args.remat, "grad_chunk": args.grad_chunk or None,
        "device_kind": jax.devices()[0].device_kind,
    }
    return record


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--workers", type=int, default=256)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--model", default="resnet20",
                   help="zoo name (resnet20|resnet50|vgg16|wrn|mlp); "
                        "resnet50 + --image-size 224 is the BASELINE "
                        "config-5 scale probe")
    p.add_argument("--image-size", type=int, default=32, dest="image_size")
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--steps", type=int, default=4,
                   help="train steps per timed chain (min 1)")
    p.add_argument("--reps", type=int, default=2)
    p.add_argument("--remat", action="store_true",
                   help="block-level rematerialization — required to fit the "
                        "full 256x32 config in one v5e's HBM")
    p.add_argument("--grad-chunk", type=int, default=0, dest="grad_chunk",
                   help="workers per fwd/bwd slab (0 = all at once)")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--out", default=None)
    args = p.parse_args()
    args.steps = max(1, args.steps)
    from matcha_tpu.utils import pin_platform

    pin_platform(args.platform)
    record = measure(args)
    print(json.dumps(record))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)


if __name__ == "__main__":
    main()
