#!/usr/bin/env python
"""Render the committed experiment artifacts as figures.

The reference's only observability is per-rank CSV logs the user eyeballs
(/root/reference/util.py:378-419); the paper's results are accuracy-vs-epoch
and accuracy-vs-communication figures.  This tool closes that gap for the
artifacts this repo commits:

* ``budget_sweep.json``  → test-accuracy vs epoch, one line per run
* ``time_to_acc.json``   → accuracy curves + wall-clock-to-target bars with
                           the comm/compute split that carries the artifact's
                           finding (comm is ~2% on-chip, CHOCO's encode ~26%)
* ``baselines_converge.jsonl`` → the converge-tier curves (64-worker
                           compression study: CHOCO's shard-size plateau vs
                           the uncompressed control reaching target)
* a Recorder run dir (``--run-dir``) → the reference-compatible CSV series

Design notes: colors are assigned to *entities* (dpsgd, matcha-0.5, ...) via
a fixed map so the same run wears the same hue in every figure; single hue
order from a colorblind-validated categorical palette; one y-axis per figure;
the numeric tables remain the committed JSONs (this renders, never replaces).

Output: PNGs under ``benchmarks/plots/`` (or ``--out-dir``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

# fixed entity → hue map (validated categorical palette, fixed slot order;
# color follows the run identity, never its rank in any one figure)
COLORS = {
    "dpsgd": "#2a78d6",
    "matcha-0.5": "#eb6834",
    "choco-0.5": "#1baf7a",
    "matcha-0.1": "#eda100",
    "matcha-0.25": "#e87ba4",
    "matcha-1.0": "#008300",
    # backend variants wear their parent algorithm's hue (same entity; the
    # bar tick label carries the backend distinction)
    "dpsgd-skip": "#2a78d6",
    "matcha-0.5-skip": "#eb6834",
}
INK = "#0b0b0b"
INK_2 = "#52514e"
GRID = "#e5e4e0"


def _style(ax, title, xlabel, ylabel):
    ax.set_title(title, color=INK, fontsize=11, loc="left")
    ax.set_xlabel(xlabel, color=INK_2, fontsize=9)
    ax.set_ylabel(ylabel, color=INK_2, fontsize=9)
    ax.grid(True, color=GRID, linewidth=0.8, zorder=0)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(GRID)
    ax.tick_params(colors=INK_2, labelsize=8)


def _acc_axes(ax, runs, title, target=None, dashed=()):
    # runs named in ``dashed`` draw last with a dash pattern: used when two
    # runs provably coincide (budget 1.0 ≡ D-PSGD: same flags, same seed) so
    # the covered line stays visible instead of silently vanishing
    for r in sorted(runs, key=lambda r: r["run"] in dashed):
        curve = r["test_acc_curve"]
        epochs = range(1, len(curve) + 1)
        c = COLORS.get(r["run"], INK_2)
        style = dict(linestyle=(0, (4, 3)), zorder=4) if r["run"] in dashed \
            else dict(zorder=3)
        ax.plot(epochs, curve, color=c, linewidth=2, label=r["run"], **style)
    if target is not None:
        ax.axhline(target, color=INK_2, linewidth=1, linestyle=(0, (4, 3)),
                   zorder=2)
        ax.annotate(f"target {target}", xy=(1, target),
                    xytext=(2, -10), textcoords="offset points",
                    color=INK_2, fontsize=8)
    _style(ax, title, "epoch", "test accuracy")
    ax.set_ylim(0.0, 1.05)
    ax.legend(frameon=False, fontsize=8, labelcolor=INK_2, loc="lower right")


def plot_budget_sweep(path, out_dir):
    with open(path) as f:
        d = json.load(f)
    fig, ax = plt.subplots(figsize=(6.4, 4.0), dpi=150)
    _acc_axes(ax, d["runs"],
              "MATCHA budget sweep vs D-PSGD — test accuracy by epoch",
              dashed=("dpsgd",))
    out = os.path.join(out_dir, "budget_sweep.png")
    fig.tight_layout()
    fig.savefig(out)
    plt.close(fig)
    return out


def plot_time_to_acc(path, out_dir):
    with open(path) as f:
        d = json.load(f)
    runs = d["runs"]
    fig, (ax1, ax2) = plt.subplots(
        1, 2, figsize=(10.0, 4.0), dpi=150,
        gridspec_kw={"width_ratios": [3, 2]})
    # backend variants (-skip) rerun the same experiment through a different
    # compiled program: same seed, but f32 reassociation drifts the
    # trajectory — shown dashed in the parent algorithm's hue
    _acc_axes(ax1, runs, "Accuracy by epoch", target=d["target_acc"],
              dashed=tuple(r["run"] for r in runs if r["run"].endswith("-skip")))

    # wall-clock to target, split into comm + everything else (the artifact's
    # finding lives in this split); white seams keep segments separable
    reached = [r for r in runs if r["reached"]]
    if not reached:
        # a legitimate artifact shape (--target too high for --epochs):
        # keep the accuracy panel, say so in the empty bars panel
        ax2.text(0.5, 0.5, "no run reached the target", transform=ax2.transAxes,
                 ha="center", color=INK_2, fontsize=9)
        _style(ax2, f"Wall-clock to {d['target_acc']} accuracy", "seconds", "")
        fig.tight_layout()
        out = os.path.join(out_dir, "time_to_acc.png")
        fig.savefig(out)
        plt.close(fig)
        return out
    ys = range(len(reached))
    comm = [r["comm_time_to_target_s"] for r in reached]
    rest = [r["time_to_target_s"] - r["comm_time_to_target_s"] for r in reached]
    cols = [COLORS.get(r["run"], INK_2) for r in reached]
    # color follows the run; the comm component is the same hue with a
    # texture (not a new color), so the split never reads as a new entity
    ax2.barh(ys, rest, height=0.55, color=cols,
             edgecolor="white", linewidth=1.5, zorder=3)
    ax2.barh(ys, comm, left=rest, height=0.55, color=cols, hatch="///",
             edgecolor="white", linewidth=1.5, zorder=3)
    from matplotlib.patches import Patch

    legend_handles = [
        Patch(facecolor=INK_2, label="compute + eval"),
        Patch(facecolor=INK_2, hatch="///", edgecolor="white", label="comm"),
    ]
    for y, r in zip(ys, reached):
        ax2.annotate(
            f"{r['time_to_target_s']:.0f} s · {r['epochs_to_target']} ep · "
            f"comm {100 * r['comm_time_to_target_s'] / r['time_to_target_s']:.0f}%",
            xy=(r["time_to_target_s"], y), xytext=(4, 0),
            textcoords="offset points", va="center", color=INK_2, fontsize=8)
    ax2.set_yticks(list(ys), [r["run"] for r in reached])
    _style(ax2, f"Wall-clock to {d['target_acc']} accuracy", "seconds", "")
    ax2.set_xlim(0, max(r["time_to_target_s"] for r in reached) * 1.45)
    # below the axes, right-aligned: every in-axes or title-row placement
    # collides with a bar annotation or the title at some data shape
    ax2.legend(handles=legend_handles, frameon=False, fontsize=8,
               labelcolor=INK_2, loc="upper right", ncols=2,
               bbox_to_anchor=(1.0, -0.14), borderaxespad=0.0)
    fig.tight_layout()
    out = os.path.join(out_dir, "time_to_acc.png")
    fig.savefig(out, bbox_inches="tight")  # include the below-axes legend
    plt.close(fig)
    return out


def plot_baselines_converge(path, out_dir):
    """Converge-tier curves from the JSONL (one record per run; repeated
    configs are distinct attempts and get an ``#k`` suffix).  Entities here
    are configs, not the sweep algorithms — hues assigned by first
    appearance from the same fixed palette order."""
    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    records = [r for r in records if "test_acc_curve" in r]
    if not records:
        # smoke/error records carry no curves: nothing to draw is a benign
        # outcome for this artifact, not a crash (main() keeps going)
        print(f"# no converge records with curves in {path}", file=sys.stderr)
        return None
    palette = list(dict.fromkeys(COLORS.values()))  # dedupe aliased hues
    # repeat attempts of one config share its hue but get progressively
    # sparser dashes so #2 and #3 stay tellable apart
    dashes = ["-", (0, (4, 3)), (0, (1, 2)), (0, (6, 2, 1, 2))]
    seen: dict = {}
    fig, ax = plt.subplots(figsize=(7.2, 4.2), dpi=150)
    for r in records:
        n = seen.setdefault(r["config"], {"count": 0,
                                          "color": palette[len(seen) % len(palette)]})
        n["count"] += 1
        label = r["config"] if n["count"] == 1 else f"{r['config']} #{n['count']}"
        curve = r["test_acc_curve"]
        ax.plot(range(1, len(curve) + 1), curve, color=n["color"], linewidth=2,
                linestyle=dashes[(n["count"] - 1) % len(dashes)],
                label=label, zorder=3)
    target = records[0].get("target_acc")
    if target is not None:
        ax.axhline(target, color=INK_2, linewidth=1, linestyle=(0, (4, 3)),
                   zorder=2)
        ax.annotate(f"target {target}", xy=(1, target), xytext=(2, -10),
                    textcoords="offset points", color=INK_2, fontsize=8)
    _style(ax, "Converge tier — test accuracy by epoch", "epoch",
           "test accuracy")
    ax.set_ylim(0.0, 1.05)
    # center-right: upper-left collides with the target annotation, and the
    # curves cluster along the bottom and the upper-right corner
    ax.legend(frameon=False, fontsize=8, labelcolor=INK_2, loc="center right")
    out = os.path.join(out_dir, "baselines_converge.png")
    fig.tight_layout()
    fig.savefig(out)
    plt.close(fig)
    return out


def plot_fused_sweep(path, out_dir):
    """Per-step kernel tuning grid (fused_sweep.json): steps/s vs w_window,
    one line per block_d that compiled; the north star (5,000) and the
    per-step MXU roofline (~5,500 on v5e, benchmarks/ROOFLINE.md) as
    reference lines.  Entities are block sizes — fixed hues, direct-labeled
    at the line ends so identity never rides on color alone."""
    with open(path) as f:
        d = json.load(f)
    # the main grid plus any follow-up sweep rows recorded into the same
    # artifact (r4 added followup_grid: larger windows + the 5120 hang);
    # duplicate (block_d, w_window) keeps the first (main-grid) measurement
    rows = list(d.get("grid", []))
    rows += d.get("followup_grid", {}).get("grid", [])
    ok: dict = {}
    failed: set = set()
    for g in rows:
        if "steps_per_s" in g:
            ok.setdefault((g["block_d"], g["w_window"]), g["steps_per_s"])
        else:
            failed.add(g["block_d"])
    if not ok:
        print(f"# no successful grid points in {path}", file=sys.stderr)
        return None
    by_bd: dict = {}
    for (bd, w), v in ok.items():
        by_bd.setdefault(bd, []).append((w, v))
    failed_bd = sorted(failed - set(by_bd))
    # fixed entity → hue (module design note: color follows identity, never
    # rank — a rerun where one block size fails must not repaint the rest)
    bd_hues = {2048: "#2a78d6", 4096: "#eb6834", 8192: "#1baf7a",
               5120: "#eda100", 6144: "#e87ba4"}
    fig, ax = plt.subplots(figsize=(6.8, 4.2), dpi=150)
    for bd, pts in sorted(by_bd.items()):
        pts.sort()
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        hue = bd_hues.get(bd, INK_2)
        ax.plot(xs, ys, color=hue, linewidth=2, marker="o",
                markersize=5, zorder=3, label=f"block_d {bd}")
        ax.annotate(f"block_d {bd}", xy=(xs[-1], ys[-1]), xytext=(6, 0),
                    textcoords="offset points", va="center",
                    color=hue, fontsize=8)
    for yval, name in ((5000.0, "north star 5,000"),
                       (5500.0, "per-step roofline ~5,500")):
        ax.axhline(yval, color=INK_2, linewidth=1, linestyle=(0, (4, 3)),
                   zorder=2)
        ax.annotate(name, xy=(1, yval), xytext=(2, 4),
                    textcoords="offset points", color=INK_2, fontsize=8)
    if failed_bd:
        ax.annotate("no line (compile failure): block_d " +
                    ", ".join(str(b) for b in failed_bd),
                    xy=(0.98, 0.04), xycoords="axes fraction", ha="right",
                    color=INK_2, fontsize=8)
    ax.set_xscale("log", base=2)
    ax.set_xticks(sorted({p[0] for pts in by_bd.values() for p in pts}))
    ax.get_xaxis().set_major_formatter(matplotlib.ticker.ScalarFormatter())
    dev = d.get("device_kind", "")
    _style(ax, f"Fused per-step kernel sweep — gossip-steps/s ({dev})",
           "w_window (W_t per grid visit)", "gossip-steps/s")
    ax.legend(frameon=False, fontsize=8, labelcolor=INK_2, loc="lower left")
    out = os.path.join(out_dir, "fused_sweep.png")
    fig.tight_layout()
    fig.savefig(out)
    plt.close(fig)
    return out


def plot_run_dir(run_dir, out_dir):
    """Plot a Recorder output dir — the reference's per-rank series naming
    (util.py:410-416): ``*-tacc.log`` test accuracy, ``*-losses.log`` train
    loss, one float per line per epoch, one file per rank.  All ranks are one
    entity (the same measure), so they share one hue at reduced opacity."""
    import glob

    tacc_files = sorted(glob.glob(os.path.join(run_dir, "*-tacc.log")))
    loss_files = sorted(glob.glob(os.path.join(run_dir, "*-losses.log")))
    if not tacc_files and not loss_files:
        raise FileNotFoundError(f"no Recorder *-tacc.log / *-losses.log in {run_dir}")
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(10.0, 4.0), dpi=150)
    for ax, files, name in ((ax1, tacc_files, "test accuracy"),
                            (ax2, loss_files, "train loss")):
        for f in files:
            with open(f) as fh:
                series = [float(v) for v in fh if v.strip()]
            ax.plot(range(1, len(series) + 1), series, color=COLORS["dpsgd"],
                    alpha=max(0.25, 1.0 / max(len(files), 1)),
                    linewidth=2, zorder=3)
        _style(ax, f"{name} ({len(files)} ranks)", "epoch", name)
    fig.tight_layout()
    out = os.path.join(out_dir, "recorder_run.png")
    fig.savefig(out)
    plt.close(fig)
    return out


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    p = argparse.ArgumentParser()
    p.add_argument("--sweep", default=os.path.join(here, "budget_sweep.json"))
    p.add_argument("--tta", default=os.path.join(here, "time_to_acc.json"))
    p.add_argument("--converge",
                   default=os.path.join(here, "baselines_converge.jsonl"))
    p.add_argument("--fused-sweep",
                   default=os.path.join(here, "fused_sweep.json"))
    p.add_argument("--run-dir", default=None,
                   help="a Recorder output dir to plot instead of the artifacts")
    p.add_argument("--out-dir", default=os.path.join(here, "plots"))
    args = p.parse_args()

    os.makedirs(args.out_dir, exist_ok=True)
    outs = []
    if args.run_dir:
        outs.append(plot_run_dir(args.run_dir, args.out_dir))
    else:
        if os.path.exists(args.sweep):
            outs.append(plot_budget_sweep(args.sweep, args.out_dir))
        if os.path.exists(args.tta):
            outs.append(plot_time_to_acc(args.tta, args.out_dir))
        if os.path.exists(args.converge):
            out = plot_baselines_converge(args.converge, args.out_dir)
            if out:
                outs.append(out)
        if os.path.exists(args.fused_sweep):
            out = plot_fused_sweep(args.fused_sweep, args.out_dir)
            if out:
                outs.append(out)
    for o in outs:
        print(o)
    if not outs:
        print("nothing to plot", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
