#!/usr/bin/env python
"""CHOCO encode cost on hardware: exact vs approximate top-k.

``time_to_acc.json`` showed CHOCO's top-k encode is a real ~26% share of its
epoch time — the one place compression itself is the bottleneck on-chip.
``top_k_approx`` (jax.lax.approx_max_k, the TPU PartialReduce lowering) was
added on the δ-contraction argument in ops/compress.py; this harness measures
what it actually buys at the BASELINE config-4 shape (64 workers × ResNet-20,
ratio 0.9 ⇒ k = 27,325 of 273,258 per worker).

One JSON line per compressor: encode wall-clock (best of --reps, forced
readback) and the ratio against exact ``top_k``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--workers", type=int, default=64)
    p.add_argument("--dim", type=int, default=273258)
    p.add_argument("--ratio", type=float, default=0.9)
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--out", default=None)
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    args = p.parse_args()
    if args.reps < 1:
        p.error("--reps must be >= 1 (best-of-0 would emit Infinity, "
                "which is not valid JSON)")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from matcha_tpu.utils import pin_platform

    pin_platform(args.platform)
    import jax
    import jax.numpy as jnp

    from matcha_tpu.ops import select_compressor

    x = jax.random.normal(jax.random.PRNGKey(0), (args.workers, args.dim),
                          jnp.float32)
    jax.block_until_ready(x)
    key = jax.random.PRNGKey(1)

    results = {}
    for name in ("top_k", "top_k_approx", "random_k", "top_k_q8"):
        comp = select_compressor(name)

        @jax.jit
        def enc(x, key, comp=comp):
            vals, idx = comp(x, args.ratio, key)
            # force a readback that depends on the whole encode (tunneled-TPU
            # rule — see bench.py): sum of values + first index column
            return (jnp.sum(vals.astype(jnp.float32))
                    + jnp.sum(idx[:, :1].astype(jnp.float32)))

        try:
            float(enc(x, key))  # compile + warm
            best = float("inf")
            for _ in range(args.reps):
                t0 = time.perf_counter()
                float(enc(x, key))
                best = min(best, time.perf_counter() - t0)
            results[name] = round(best * 1e3, 3)  # ms per encode
        except Exception as e:  # noqa: BLE001 — record, keep measuring others
            results[name] = f"{type(e).__name__}: {str(e)[:200]}"

    rec = {
        "metric": f"CHOCO encode ms @ {args.workers} workers x D={args.dim}, "
                  f"ratio {args.ratio}",
        "encode_ms": results,
        "device_kind": jax.devices()[0].device_kind,
    }
    # approximate-path quality, measured where it is real (on CPU the op
    # falls back to exact top-k and recall is trivially 1.0 — the unit test
    # cannot check this, tests/test_ops.py documents that): recall vs exact
    # top-k and the realized energy-capture ratio, the δ in CHOCO's
    # contraction assumption
    try:
        from matcha_tpu.ops import batched_top_k, batched_top_k_approx

        @jax.jit
        def quality(x):
            ev, ei = batched_top_k(x, args.ratio)
            av, ai = batched_top_k_approx(x, args.ratio)
            k = ei.shape[-1]
            # membership via a dense [N, D] mask (a [N, k, k] pairwise
            # compare would be ~50 G elements at the config-4 shape)
            rows = jnp.arange(x.shape[0])[:, None]
            mask = jnp.zeros(x.shape, jnp.bool_).at[rows, ei].set(True)
            hits = jnp.sum(mask[rows, ai], axis=-1)
            return (jnp.mean(hits / k),
                    jnp.mean(jnp.sum(av**2, -1) / jnp.sum(ev**2, -1)))

        recall, energy = quality(x)
        rec["approx_recall_vs_exact"] = round(float(recall), 4)
        rec["approx_energy_capture_vs_exact"] = round(float(energy), 4)
    except Exception as e:  # noqa: BLE001
        rec["approx_quality_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    exact, approx = results.get("top_k"), results.get("top_k_approx")
    if isinstance(exact, float) and isinstance(approx, float) and approx > 0:
        rec["approx_speedup_vs_exact"] = round(exact / approx, 2)
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
