#!/usr/bin/env python
"""Run the five BASELINE.json reference configurations end-to-end.

The reference publishes no numbers (BASELINE.md), so what this harness
establishes is that every configuration the reference can express runs in
this framework, and what its measured comp/comm/epoch split and accuracy
trajectory are on the current hardware.  Real CIFAR/ImageNet data is not
downloadable in this environment; synthetic stand-ins with the right input
shapes exercise the identical compiled program shapes (model × workers ×
schedule).  Three tiers:

* ``--scale smoke``    — 1-2 epochs, chance-level accuracy by design: a
  **compile-smoke regression gate** only (the program shapes build, step,
  and record).  It demonstrates nothing about learning.
* ``--scale converge`` — the VERDICT r2 item-3 tier: same models and worker
  counts, separable synthetic clusters, enough epochs that every run must
  end far above chance (target ≥0.9); per-epoch accuracy curves are recorded
  so the MATCHA-vs-D-PSGD ordering is visible.  Artifact:
  ``baselines_converge.jsonl``.
* ``--scale full --data-root <npz dir>`` — the real experiment on a machine
  with the actual datasets.

Output: one JSON line per config with the recorder's series.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from matcha_tpu.train import TrainConfig, train  # noqa: E402

# The five reference configs (BASELINE.md; reference flags in parentheses).
CONFIGS = {
    # 1. ResNet / CIFAR-10, 8 workers, D-PSGD FixedProcessor graphid 0
    "dpsgd-resnet-cifar10-8w": TrainConfig(
        name="dpsgd-resnet-cifar10-8w", model="res", dataset="cifar10",
        num_workers=8, graphid=0, matcha=False, fixed_mode="all",
        lr=0.8, batch_size=32,
    ),
    # 2. VGG-16 / CIFAR-10, 8 workers, MATCHA budget 0.5
    "matcha-vgg16-cifar10-8w": TrainConfig(
        name="matcha-vgg16-cifar10-8w", model="VGG", dataset="cifar10",
        num_workers=8, graphid=0, matcha=True, budget=0.5,
        lr=0.8, batch_size=32,
    ),
    # 3. WRN-28-10 / CIFAR-100, 16 workers, MATCHA on the ER graph (zoo id 4)
    "matcha-wrn-cifar100-16w": TrainConfig(
        name="matcha-wrn-cifar100-16w", model="wrn", dataset="cifar100",
        num_workers=16, graphid=4, matcha=True, budget=0.5,
        lr=0.8, batch_size=32,
    ),
    # 4. ResNet / CIFAR-10, 64 workers, CHOCO + top-k
    "choco-resnet-cifar10-64w": TrainConfig(
        name="choco-resnet-cifar10-64w", model="resnet20", dataset="cifar10",
        num_workers=64, graphid=None, topology="geometric",
        matcha=True, budget=0.5, communicator="choco", compress_ratio=0.9,
        lr=0.8, batch_size=32,
    ),
    # 5. ResNet-50 / ImageNet, 256 workers, MATCHA sweep point
    "matcha-resnet50-imagenet-256w": TrainConfig(
        name="matcha-resnet50-imagenet-256w", model="resnet50",
        dataset="imagenet", num_workers=256, graphid=None,
        topology="geometric", matcha=True, budget=0.5,
        lr=0.8, batch_size=8,
    ),
    # Diagnostic (not one of the five BASELINE configs): config 4 without
    # compression — same 64 workers / ResNet-20 / MATCHA-0.5 geometric
    # graph, decen instead of CHOCO.  Separates "64-way conv training
    # learns in this framework" from "top-k-compressed consensus needs
    # bigger shards/longer horizons" when the config-4 converge runs
    # plateau (see CONVERGE_OVERRIDES note).
    "matcha-resnet-cifar10-64w-diag": TrainConfig(
        name="matcha-resnet-cifar10-64w-diag", model="resnet20",
        dataset="cifar10", num_workers=64, graphid=None,
        topology="geometric", matcha=True, budget=0.5,
        lr=0.8, batch_size=32,
    ),
    # Diagnostic: REAL pixels end to end.  The reference's EMNIST/MLP config
    # (util.py:165-254 + select_model 'mlp', util.py:267-268) on the only real
    # image pixels available without egress — scikit-learn's bundled UCI
    # handwritten digits (1,797 8×8 images; see data/datasets.py uci_digits).
    # Same MATCHA-0.5 gossip machinery as the paper configs; closes the
    # "no real pixels ever trained" gap (VERDICT r3 missing-6) at the scale
    # the environment permits.
    "matcha-mlp-digits-8w": TrainConfig(
        name="matcha-mlp-digits-8w", model="mlp", dataset="digits",
        num_workers=8, graphid=0, matcha=True, budget=0.5,
        lr=0.1, batch_size=16,
    ),
    # Diagnostic: real-RGB-pixel conv configs (VERDICT r4 item 4).  No real
    # CIFAR archive exists in-environment — the repo's CIFAR fixtures are
    # format-faithful NOISE (tests/fixtures/make_fixtures.py) — so
    # photo_patches (one class per real photograph baked into
    # site-packages, spatially disjoint train/test crops) is the largest
    # real-pixel conv task obtainable offline.  Shape of the reference's
    # core experiment (train_mpi.py:58-168): ResNet-20, 8 workers, D-PSGD
    # vs MATCHA 0.5 vs all-reduce control, augmentation on.
    "dpsgd-resnet-photo-8w": TrainConfig(
        name="dpsgd-resnet-photo-8w", model="resnet20",
        dataset="photo_patches", num_workers=8, graphid=0, matcha=False,
        fixed_mode="all", lr=0.1, batch_size=32, augment=True,
    ),
    "matcha-resnet-photo-8w": TrainConfig(
        name="matcha-resnet-photo-8w", model="resnet20",
        dataset="photo_patches", num_workers=8, graphid=0, matcha=True,
        budget=0.5, lr=0.1, batch_size=32, augment=True,
    ),
    "central-resnet-photo-8w": TrainConfig(
        name="central-resnet-photo-8w", model="resnet20",
        dataset="photo_patches", num_workers=8, graphid=0, matcha=False,
        communicator="centralized", lr=0.1, batch_size=32, augment=True,
    ),
    # Diagnostic: config 4 with compression warmup (the r5 mitigation for
    # the top-k-10% cold start): ratio ramps 0→0.9 over 4 epochs, then the
    # reference-exact compressed gossip runs.  Same shards/graph as the
    # plain converge rerun, so the pair isolates what warmup buys.
    "choco-resnet-cifar10-64w-warmup": TrainConfig(
        name="choco-resnet-cifar10-64w-warmup", model="resnet20",
        dataset="cifar10", num_workers=64, graphid=None,
        topology="geometric", matcha=True, budget=0.5,
        communicator="choco", compress_ratio=0.9,
        compress_warmup_epochs=4, lr=0.8, batch_size=32,
    ),
    # Diagnostic: the control the r5 warmup A/B is missing (ADVICE r5).
    # Fixed-schedule CHOCO — all matchings every step, γ=0.1 — on the same
    # 64-worker geometric graph: the regime where CHOCO's telescoping-s
    # assumption actually holds (W is constant).  Same 4-epoch compression
    # warmup as the A/B arm, so the compression trajectory is identical and
    # ONLY the schedule differs.  Separates "γ-damped mixing is too slow at
    # 64 workers" (this run also stalls) from "the time-varying-W
    # accumulator cross-terms are the bias" (this run learns while the
    # MATCHA-scheduled one stalls).
    "choco-resnet-cifar10-64w-fixed": TrainConfig(
        name="choco-resnet-cifar10-64w-fixed", model="resnet20",
        dataset="cifar10", num_workers=64, graphid=None,
        topology="geometric", matcha=False, fixed_mode="all",
        communicator="choco", compress_ratio=0.9, consensus_lr=0.1,
        compress_warmup_epochs=4, lr=0.8, batch_size=32,
    ),
    # Diagnostic: the 512-images/worker point of the CHOCO shard-size sweep
    # (64→256→512; VERDICT r4 item 1's alternate done-criterion).  Plain
    # reference semantics (no warmup), γ=0.1.  TPU-window only — ~8 h of
    # pure CPU otherwise.
    "choco-resnet-cifar10-64w-512shard": TrainConfig(
        name="choco-resnet-cifar10-64w-512shard", model="resnet20",
        dataset="cifar10", num_workers=64, graphid=None,
        topology="geometric", matcha=True, budget=0.5,
        communicator="choco", compress_ratio=0.9, lr=0.8, batch_size=32,
    ),
}

SMOKE_OVERRIDES = {
    # synthetic stand-ins with the dataset's input shape; tiny epochs.
    # Accuracy here is chance level BY DESIGN — this tier only gates that the
    # program shapes compile and step (see module docstring).
    "dpsgd-resnet-cifar10-8w": dict(dataset="synthetic_image", epochs=2),
    "matcha-vgg16-cifar10-8w": dict(dataset="synthetic_image", epochs=2),
    "matcha-wrn-cifar100-16w": dict(dataset="synthetic_image", epochs=1,
                                    batch_size=8),
    "choco-resnet-cifar10-64w": dict(dataset="synthetic_image", epochs=1,
                                     batch_size=8),
    "matcha-resnet50-imagenet-256w": dict(dataset="synthetic_image", epochs=1,
                                          batch_size=2, model="resnet20",
                                          num_workers=64),
    "matcha-resnet-cifar10-64w-diag": dict(dataset="synthetic_image", epochs=1,
                                           batch_size=8),
    "matcha-mlp-digits-8w": dict(epochs=2),  # real data IS the smoke payload
    # real pixels ARE the smoke payload here too; tiny crop counts
    "dpsgd-resnet-photo-8w": dict(
        epochs=1, batch_size=8,
        dataset_kwargs={"train_per_class": 32, "test_per_class": 8}),
    "matcha-resnet-photo-8w": dict(
        epochs=1, batch_size=8,
        dataset_kwargs={"train_per_class": 32, "test_per_class": 8}),
    "central-resnet-photo-8w": dict(
        epochs=1, batch_size=8,
        dataset_kwargs={"train_per_class": 32, "test_per_class": 8}),
    "choco-resnet-cifar10-64w-warmup": dict(
        dataset="synthetic_image", epochs=1, batch_size=8,
        compress_warmup_epochs=1),
    "choco-resnet-cifar10-64w-fixed": dict(
        dataset="synthetic_image", epochs=1, batch_size=8,
        compress_warmup_epochs=1),
    "choco-resnet-cifar10-64w-512shard": dict(
        dataset="synthetic_image", epochs=1, batch_size=8),
}

# Converging tier: separable synthetic clusters (the budget_sweep/_miniature
# recipe: separation 40 gives a conv stem a per-pixel signal it can fit
# within a miniature epoch budget), real models and worker counts, lr sized
# for stability on the synthetic task.  Every run must end ≫ chance (0.1).
_CONVERGE_DATA = dict(
    dataset="synthetic_image",
    dataset_kwargs={"num_train": 4096, "num_test": 1024, "separation": 40.0},
    lr=0.05, base_lr=0.05, batch_size=8, eval_every=1,
    # comm split ON (VERDICT r3 weak-2): converge artifacts must carry real
    # comm/encode shares, not 0.0 — costs one extra gossip chain per epoch
    measure_comm_split=True,
)
CONVERGE_OVERRIDES = {
    "dpsgd-resnet-cifar10-8w": dict(_CONVERGE_DATA, epochs=8),
    "matcha-vgg16-cifar10-8w": dict(_CONVERGE_DATA, epochs=8),
    # VERDICT r2 item 3 names these two: real WRN-28-10 at 16 workers and
    # the 64-worker CHOCO ResNet-20 (compressed gossip) must *learn*.
    # remat: WRN-28-10's un-rematted 16-worker vmapped backward is
    # activation-heavy (32x32x160 maps); block remat keeps it inside one
    # v5e's HBM without changing the arithmetic (tested exact)
    "matcha-wrn-cifar100-16w": dict(_CONVERGE_DATA, epochs=8, remat=True),
    # 64 workers need the same *per-worker* data density that converges at
    # 16 workers (256 images each, the budget_sweep/time_to_acc recipe that
    # reaches 0.97): two probes with 64-image shards plateaued at ~0.26
    # regardless of step count (10ep/batch8 = 80 steps and 24ep/batch4 =
    # 384 steps), so the shard size, not the step budget, was the limit.
    # consensus_lr: γ=0.3 with 256-image shards rose to 0.68 by epoch 5 and
    # then DECAYED to 0.44 (r4 committed line — consensus instability
    # compounding at 64 workers; both r3 γ=0.1 probes were stable, merely
    # data-starved), so γ backs off to the reference default 0.1 and the
    # horizon stretches to 12 epochs for the slower-but-stable consensus.
    # The smaller test set keeps single-core eval FLOPs from dominating.
    "choco-resnet-cifar10-64w": dict(
        _CONVERGE_DATA, epochs=12, consensus_lr=0.1,
        dataset_kwargs={"num_train": 16384, "num_test": 256,
                        "separation": 40.0}),
    # 256 workers x 224x224 ResNet-50: remat + 32-worker fwd/bwd slabs keep
    # the folded single-chip program inside HBM (activations dominate)
    "matcha-resnet50-imagenet-256w": dict(_CONVERGE_DATA, epochs=8,
                                          batch_size=4, remat=True,
                                          grad_chunk=32),
    # uncompressed control for the config-4 plateau: same shard size
    # (64 images/worker), same graph/budget — D-PSGD-style dense averaging
    # instead of top-k-10% CHOCO
    "matcha-resnet-cifar10-64w-diag": dict(
        _CONVERGE_DATA, epochs=12, batch_size=4,
        dataset_kwargs={"num_train": 4096, "num_test": 256,
                        "separation": 40.0}),
    # real pixels (UCI digits), NOT the synthetic recipe: the dataset is the
    # point of this config, so only budget/epoch knobs are tiered here
    "matcha-mlp-digits-8w": dict(epochs=30, eval_every=1,
                                 measure_comm_split=True),
    # real RGB pixels (photo_patches), NOT the synthetic recipe: default
    # build (768+128 crops/class × 8 photos), augmentation on, comm split
    # on for the MATCHA run (conv-model comm-share data, VERDICT r4 item 5)
    "dpsgd-resnet-photo-8w": dict(epochs=15, eval_every=1, lr=0.1,
                                  measure_comm_split=False),
    "matcha-resnet-photo-8w": dict(epochs=15, eval_every=1, lr=0.1,
                                   measure_comm_split=True),
    "central-resnet-photo-8w": dict(epochs=15, eval_every=1, lr=0.1,
                                    measure_comm_split=False),
    # config-4 shards/graph + 4-epoch ratio ramp; γ stays at the reference
    # default (the γ=0.3 run's late-epoch collapse was compression×large-γ —
    # with warmup the dense phase does the fast consensus instead)
    "choco-resnet-cifar10-64w-warmup": dict(
        _CONVERGE_DATA, epochs=12, consensus_lr=0.1,
        compress_warmup_epochs=4,
        dataset_kwargs={"num_train": 16384, "num_test": 256,
                        "separation": 40.0}),
    # same data/shards and the same 4-epoch ratio ramp as the warmup-quick
    # A/B arm (the setup where dense gossip reaches 0.9513 and
    # MATCHA-scheduled CHOCO stalls at 0.135) — only the schedule differs:
    # fixed all-matchings W every step
    "choco-resnet-cifar10-64w-fixed": dict(
        _CONVERGE_DATA, epochs=12, batch_size=4, consensus_lr=0.1,
        compress_warmup_epochs=4,
        dataset_kwargs={"num_train": 4096, "num_test": 256,
                        "separation": 40.0}),
    # 512 images/worker, same step budget per image (epochs scale down is
    # NOT applied: more steps is the point of bigger shards)
    "choco-resnet-cifar10-64w-512shard": dict(
        _CONVERGE_DATA, epochs=12, consensus_lr=0.1,
        dataset_kwargs={"num_train": 32768, "num_test": 256,
                        "separation": 40.0}),
}

# Exact mirror of the uncompressed diag control's converge setup (64-image
# shards, batch 4, 12 epochs — the config where dense gossip reaches 0.9513)
# but CHOCO + 4-epoch compression warmup: the tightest A/B for what warmup
# buys against the committed 0.26 plateau rows, and small enough to finish
# on the 1-core host.  Registered as its own converge entry.
CONFIGS["choco-resnet-cifar10-64w-warmup-quick"] = dataclasses.replace(
    CONFIGS["choco-resnet-cifar10-64w-warmup"],
    name="choco-resnet-cifar10-64w-warmup-quick")
SMOKE_OVERRIDES["choco-resnet-cifar10-64w-warmup-quick"] = dict(
    SMOKE_OVERRIDES["choco-resnet-cifar10-64w-warmup"])
CONVERGE_OVERRIDES["choco-resnet-cifar10-64w-warmup-quick"] = dict(
    _CONVERGE_DATA, epochs=12, batch_size=4, consensus_lr=0.1,
    compress_warmup_epochs=4,
    dataset_kwargs={"num_train": 4096, "num_test": 256, "separation": 40.0})


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--scale", choices=["smoke", "converge", "full"],
                   default="smoke")
    p.add_argument("--data-root", default=None, help="dir of .npz datasets (full scale)")
    p.add_argument("--only", default=None, help="comma-separated config names")
    p.add_argument("--target", type=float, default=0.9,
                   help="converge tier: accuracy every run must reach")
    p.add_argument("--out", default=None,
                   help="also append JSON lines to this file")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="pin the JAX backend via jax.config (the container's "
                        "sitecustomize overrides JAX_PLATFORMS env vars, and "
                        "a dead TPU tunnel hangs backend init — pass cpu to "
                        "run while the tunnel is down)")
    p.add_argument("--no-scan-epoch", action="store_true",
                   help="compile one train step instead of the whole epoch "
                        "scan — slower steps, minutes less XLA-CPU compile; "
                        "use for converge runs on a 1-core host")
    args = p.parse_args()
    from matcha_tpu.utils import pin_platform

    pin_platform(args.platform)

    names = list(CONFIGS) if args.only is None else args.only.split(",")
    failures = 0

    # Best-effort: convert a timeout-wrapper's SIGTERM into an exception the
    # per-config handler below records (and flushes) before the process
    # exits.  Python only delivers the signal at a bytecode boundary — TERM
    # arriving mid-XLA-call (the tunnel's common stall mode) stays pending
    # until the C++ call returns, and `timeout -k` may SIGKILL first; the
    # `started` breadcrumb printed before train() is the guaranteed trace.
    def _sigterm(signum, frame):
        raise TimeoutError("SIGTERM (outer timeout wrapper)")

    signal.signal(signal.SIGTERM, _sigterm)
    out_f = None  # before the try: open() raising must not mask itself as UnboundLocalError
    try:
        out_f = open(args.out, "a") if args.out else None
        for cname in names:
            cfg = CONFIGS[cname]
            if args.scale == "smoke":
                cfg = dataclasses.replace(cfg, warmup=False, seed=0,
                                          **SMOKE_OVERRIDES[cname])
            elif args.scale == "converge":
                cfg = dataclasses.replace(cfg, warmup=False, seed=0,
                                          **CONVERGE_OVERRIDES[cname])
            elif args.data_root is not None:  # full scale with real npz data
                cfg = dataclasses.replace(
                    cfg, datasetRoot=os.path.join(args.data_root, f"{cfg.dataset}.npz")
                )
            if args.no_scan_epoch:
                cfg = dataclasses.replace(cfg, scan_epoch=False)
            t0 = time.time()
            timed_out = False
            # stderr breadcrumb (stdout and the JSONL stay records-only: a
            # `> results.jsonl` caller must not get comment lines): a
            # SIGKILLed run still shows which config was in flight
            print(f"# started {cname} ({args.scale})", file=sys.stderr,
                  flush=True)
            try:
                hist = train(cfg).history
            except Exception as e:  # one config failing must not eat the rest
                failures += 1
                timed_out = isinstance(e, TimeoutError)
                record = {
                    "config": cname, "scale": args.scale,
                    "wall_s": round(time.time() - t0, 2),
                    "error": f"{type(e).__name__}: {e}",
                }
            else:
                record = {
                    "config": cname,
                    "scale": args.scale,
                    "epochs": len(hist),
                    "wall_s": round(time.time() - t0, 2),
                    "final_loss": round(hist[-1]["loss"], 4),
                    "final_test_acc": round(hist[-1]["test_acc_mean"], 4),
                    "epoch_time_s": round(hist[-1]["epoch_time"], 3),
                    "comm_time_s": round(hist[-1]["comm_time"], 3),
                    "comm_share": round(
                        hist[-1]["comm_time"] / max(hist[-1]["epoch_time"], 1e-9), 4
                    ),
                    "comm_split_measured": cfg.measure_comm_split,
                }
                if args.scale == "converge":
                    curve = [round(float(h["test_acc_mean"]), 4) for h in hist]
                    reached = next((i + 1 for i, a in enumerate(curve)
                                    if a >= args.target), None)
                    record.update({
                        "test_acc_curve": curve,
                        "target_acc": args.target,
                        "target_reached": reached is not None,
                        "epochs_to_target": reached,
                    })
                    if reached is None:
                        # the tier's contract is "every run learns to
                        # target" — a miss is a gate failure, not a pass
                        failures += 1
            line = json.dumps(record)
            print(line, flush=True)
            if out_f:
                out_f.write(line + "\n")
                out_f.flush()  # a dying tunnel must not eat completed configs
            if timed_out:
                break  # the wrapper wants us gone; don't start another config
    finally:
        if out_f:
            out_f.close()
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
