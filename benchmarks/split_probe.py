#!/usr/bin/env python
"""One-question hardware probe: does column-splitting the per-step dot help?

The fused kernel's remaining ~9% to the v5e per-step ceiling is attributed
(benchmarks/ROOFLINE.md) to the per-step f32→wire cast serializing against
the MXU: within a w_window visit every step is ``cast(dot(W_t, state))`` and
the next step's dot consumes the cast's output, so Mosaic cannot overlap the
VPU cast with MXU work *of the same column range*.  Splitting the D-block's
columns in half makes the dependency per-half: the cast of half 0 can overlap
the dot of half 1 at every step.  Arithmetic is unchanged (columns of
``W @ X`` are independent; same dot shape over K, same f32 accumulation, same
per-step cast) — this is purely a scheduling question Mosaic has to answer,
so it is measured, not assumed.

Writes ``{base, split, ratio, device_kind}`` JSON to --out; exits 0 even when
inconclusive (the artifact records what happened).  Run it only on a live
tunnel (tpu_session.sh step 2.5).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

N, D, T, BD, W = 256, 273258, 2000, 4096, 8


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()
    if args.reps < 1:
        p.error("--reps must be >= 1 (best-of-0 would emit Infinity, "
                "which is not valid JSON)")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from matcha_tpu.utils import pin_platform

    pin_platform(None)  # compile cache
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    @jax.jit
    def gen():
        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        x = jax.random.normal(k1, (N, D), jnp.bfloat16)
        stk = (jax.random.normal(k2, (T, N, N), jnp.float32) * 0.01
               + jnp.eye(N)[None] * 0.9).astype(jnp.bfloat16)
        return x, stk

    x, stk = gen()
    jax.block_until_ready(x)

    def make_kernel(split):
        def _kernel(x_ref, w_ref, o_ref):
            t = pl.program_id(1)

            @pl.when(t == 0)
            def _():
                o_ref[...] = x_ref[...]

            half = BD // 2
            for k in range(W):
                if split:
                    xk = o_ref[...].astype(w_ref.dtype)
                    a0 = jnp.dot(w_ref[k], xk[:, :half],
                                 preferred_element_type=jnp.float32)
                    a1 = jnp.dot(w_ref[k], xk[:, half:],
                                 preferred_element_type=jnp.float32)
                    o_ref[:, :half] = a0.astype(o_ref.dtype)
                    o_ref[:, half:] = a1.astype(o_ref.dtype)
                else:
                    o_ref[...] = jnp.dot(
                        w_ref[k], o_ref[...].astype(w_ref.dtype),
                        preferred_element_type=jnp.float32,
                    ).astype(o_ref.dtype)
        return _kernel

    @functools.partial(jax.jit, static_argnames=("split",))
    def run(x, stk, split=False):
        return pl.pallas_call(
            make_kernel(split), grid=(pl.cdiv(D, BD), T // W),
            in_specs=[pl.BlockSpec((N, BD), lambda i, t: (0, i)),
                      pl.BlockSpec((W, N, N), lambda i, t: (t, 0, 0))],
            out_specs=pl.BlockSpec((N, BD), lambda i, t: (0, i)),
            out_shape=jax.ShapeDtypeStruct((N, D), x.dtype))(x, stk)

    def rate(split):
        g = jax.jit(lambda x: jnp.sum(run(x, stk, split=split)[:, :8]
                                      .astype(jnp.float32)))
        float(g(x))
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(g(x))
            best = min(best, time.perf_counter() - t0)
        return T / best

    rec = {"probe": "split-cast-overlap", "n": N, "d": D, "steps": T,
           "block_d": BD, "w_window": W,
           "device_kind": jax.devices()[0].device_kind}
    try:
        # whole-array equality on device (ADVICE r4: the earlier 8-column
        # f32-sum check could miss a divergence in the other 273k columns)
        y0 = run(x, stk)
        y1 = run(x, stk, split=True)
        rec["outputs_equal"] = bool(jnp.array_equal(y0, y1))
        rec["slice_sums_equal"] = rec["outputs_equal"]  # back-compat key
        del y0, y1
        rec["base_steps_per_sec"] = round(rate(False), 1)
        rec["split_steps_per_sec"] = round(rate(True), 1)
        rec["ratio"] = round(rec["split_steps_per_sec"]
                             / rec["base_steps_per_sec"], 4)
    except Exception as e:  # noqa: BLE001 — the artifact records the failure
        rec["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
